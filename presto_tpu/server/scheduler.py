"""Fairness-aware multi-tenant admission: the layer between the
serving front-end and the memory pool's strict FIFO.

Reference parity: resource groups + ``NodeScheduler`` — the
coordinator tier that decides WHOSE query runs next when demand
exceeds capacity, before per-query admission decides whether it fits
[SURVEY §2.1 resource-group row]. ``MemoryPool.reserve`` is strict
FIFO on purpose (head-of-line keeps big queries from starving), which
is exactly wrong between *tenants*: one aggressor flooding cheap
queries would fill the FIFO and starve an interactive tenant's
occasional query. This scheduler sits in front: every query first
takes a weighted-fair concurrency slot, then admits through the pool
as before.

Mechanics — classic weighted fair queuing over a condition variable:

- Each tenant carries a **virtual time**; every ENQUEUED waiter
  advances it by ``1 / weight`` (stamping at admission instead would
  give a whole burst one shared stamp and let the backlog admit
  shoulder-to-shoulder). Waiters carry their virtual *finish* time,
  and the lowest stamp among quota-eligible waiters runs next — a
  flooding tenant's vtime races ahead, so a lighter tenant's next
  query overtakes the flood's backlog (the p99-protection property
  the sustained-load bench measures).
- **Quotas** are hard gates: a tenant at ``max_concurrent`` running
  queries, or holding more than ``max_bytes`` of live memory-pool
  reservations (tenant-tagged in ``runtime/memory.py``), is skipped
  regardless of its stamp — that is the preemption rung: over-quota
  tenants lose their place in line until they release. (There is no
  mid-flight kill: a compiled XLA step runs to completion, so
  preemption happens at admission boundaries, like every other
  lifecycle control in this engine.)
- ``total_slots`` bounds overall concurrency; ``None`` leaves global
  concurrency to the memory pool and engages fairness only through
  per-tenant quotas.

Counters: ``tenant.admitted`` / ``tenant.queued`` /
``tenant.over_quota_blocked`` / ``tenant.queue_timeouts`` (each also
suffixed ``.<tenant>``), histogram ``tenant.queued_s``. Live state is
queryable as ``system.tenants`` when a server attaches the scheduler
to its session.
"""

from __future__ import annotations

import itertools
import re
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Iterable, Mapping, Optional

from presto_tpu.runtime.errors import ResourceExhausted, ServerOverloaded
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.overload import CostEwma, shed_retry_after

_NAME_RE = re.compile(r"[^A-Za-z0-9_]")


def _metric_name(tenant: str) -> str:
    """Tenant name sanitized for OpenMetrics suffixes."""
    return _NAME_RE.sub("_", tenant) or "_"


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's fairness contract: scheduling ``weight`` (share of
    contended slots), ``max_concurrent`` running queries, and
    ``max_bytes`` of live memory-pool reservations (both ``None`` =
    unlimited)."""

    name: str
    weight: float = 1.0
    max_concurrent: Optional[int] = None
    max_bytes: Optional[int] = None
    #: per-tenant SLO objectives consumed by ``runtime/health.py``'s
    #: SloTracker; ``None`` falls through to the session-wide
    #: ``slo_latency_objective_s`` / ``slo_freshness_objective_s``
    slo_latency_s: Optional[float] = None
    slo_freshness_s: Optional[float] = None
    #: brown-out policy (runtime/overload.OverloadController): while a
    #: health breach has the brown-out engaged, this tenant's NEW
    #: traffic is routed to the approx tier (``"approx"``, flagged via
    #: QueryInfo.approximate) or refused with ServerOverloaded
    #: (``"shed"``); ``None`` (the default) opts out of degradation
    brownout: Optional[str] = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name!r}: weight must be > 0")
        for f in ("slo_latency_s", "slo_freshness_s"):
            v = getattr(self, f)
            if v is not None and v <= 0:
                raise ValueError(f"tenant {self.name!r}: {f} must be > 0")
        if self.brownout not in (None, "approx", "shed"):
            raise ValueError(
                f"tenant {self.name!r}: brownout must be approx|shed|None, "
                f"got {self.brownout!r}")


class _TenantState:
    __slots__ = ("running", "peak_running", "admitted", "over_quota_blocked",
                 "queue_timeouts", "vtime")

    def __init__(self):
        self.running = 0
        self.peak_running = 0
        self.admitted = 0
        self.over_quota_blocked = 0
        self.queue_timeouts = 0
        self.vtime = 0.0


class _Waiter:
    __slots__ = ("stamp", "seq", "tenant", "counted_block")

    def __init__(self, stamp: float, seq: int, tenant: str):
        self.stamp = stamp
        self.seq = seq
        self.tenant = tenant
        self.counted_block = False

    @property
    def order(self):
        return (self.stamp, self.seq)


class FairScheduler:
    """Weighted-fair, quota-gated concurrency slots for named tenants.

    Unknown tenants auto-register with ``default_spec`` (weight 1, no
    quotas unless overridden) — a serving front-end must not 500 a new
    client, it must schedule it fairly.
    """

    def __init__(self, tenants: "Iterable[TenantSpec] | Mapping | None" = None,
                 total_slots: Optional[int] = None,
                 default_spec: Optional[TenantSpec] = None,
                 pool=None, max_tenants: int = 256,
                 global_queue_limit: Optional[int] = None,
                 tenant_queue_limit: Optional[int] = None,
                 shed_drain_limit_s: Optional[float] = None):
        self._cv = threading.Condition()
        #: load-shedding ceilings (overload rung 1; None = disabled).
        #: Over-ceiling acquires fail FAST with the retryable
        #: ServerOverloaded (HTTP 429 upstream) BEFORE a waiter is
        #: enqueued or vtime is burned — a shed leaves no ghost state.
        self.global_queue_limit = global_queue_limit
        self.tenant_queue_limit = tenant_queue_limit
        #: EWMA-cost admission: shed when the estimated backlog drain
        #: time ``(queued+1) * ewma_cost / slots`` exceeds this
        self.shed_drain_limit_s = shed_drain_limit_s
        #: per-query slot-occupancy EWMA (updated by ``slot()``) — the
        #: drain-time estimator; also exported via snapshot rows
        self.cost_ewma = CostEwma()
        self._specs: dict[str, TenantSpec] = {}
        self._states: dict[str, _TenantState] = {}
        self._waiters: list[_Waiter] = []
        self._vclock = 0.0
        self._seq = itertools.count()
        self._running_total = 0
        self.total_slots = total_slots
        self.default_spec = default_spec or TenantSpec("default")
        #: cap on auto-registered tenant names: the tenant header is
        #: client-controlled, and each name permanently allocates
        #: state, a system.tenants row, and per-tenant counters — past
        #: the cap, walk-ins pool into one shared "__overflow__" lane
        #: (still fairly scheduled, bounded cardinality, counted)
        self.max_tenants = max(1, int(max_tenants))
        #: optional MemoryPool whose tenant-tagged reservations back the
        #: byte quotas (runtime/memory.py); its release listeners kick
        #: this scheduler so byte-blocked waiters re-check promptly
        #: (detached again by close() — a listener on the process-global
        #: pool must not pin a dead scheduler forever)
        self._pool = pool
        self._pool_listener = None
        if pool is not None and hasattr(pool, "add_release_listener"):
            self._pool_listener = lambda *_: self.kick()
            pool.add_release_listener(self._pool_listener)
        if isinstance(tenants, Mapping):
            tenants = tenants.values()
        for spec in tenants or ():
            self.register(spec)

    # ---- registry --------------------------------------------------------
    def register(self, spec: TenantSpec) -> None:
        with self._cv:
            self._specs[spec.name] = spec
            self._states.setdefault(spec.name, _TenantState())

    def spec(self, tenant: str) -> TenantSpec:
        with self._cv:
            return self._spec_locked(tenant)

    def _resolve_locked(self, tenant: str) -> str:
        """Effective tenant name: unknown tenants auto-register with
        the default spec until ``max_tenants``; beyond it they pool
        into the shared ``__overflow__`` lane (the header is
        client-controlled — unbounded names must not grow state or
        metric cardinality forever)."""
        if tenant in self._specs:
            return tenant
        if len(self._specs) >= self.max_tenants:
            REGISTRY.counter("tenant.overflow").add()
            tenant = "__overflow__"
            if tenant in self._specs:
                return tenant
        s = TenantSpec(tenant, self.default_spec.weight,
                       self.default_spec.max_concurrent,
                       self.default_spec.max_bytes,
                       self.default_spec.slo_latency_s,
                       self.default_spec.slo_freshness_s,
                       self.default_spec.brownout)
        self._specs[tenant] = s
        self._states.setdefault(tenant, _TenantState())
        return tenant

    def _spec_locked(self, tenant: str) -> TenantSpec:
        return self._specs[self._resolve_locked(tenant)]

    # ---- quota / fairness predicates ------------------------------------
    def _tenant_bytes(self, tenant: str) -> int:
        if self._pool is None:
            return 0
        try:
            return self._pool.tenant_reserved_bytes(tenant)
        except Exception:  # noqa: BLE001 — quotas degrade open, not closed
            return 0

    def _under_quota(self, tenant: str) -> bool:
        spec = self._spec_locked(tenant)
        st = self._states[tenant]
        if spec.max_concurrent is not None and st.running >= spec.max_concurrent:
            return False
        if spec.max_bytes is not None and self._tenant_bytes(tenant) >= spec.max_bytes:
            return False
        return True

    def _blocker_of(self, w: _Waiter) -> Optional[str]:
        """Why ``w`` cannot be admitted right now: its own tenant is
        over quota ("quota"), the global slot pool is full ("slots"),
        or an eligible waiter with an earlier virtual finish time is
        ahead ("turn"). None = admissible. Quota verdicts are memoized
        per tenant within one call: byte quotas read the pool under
        ITS lock, and a deep queue must not pay one cross-lock probe
        per earlier waiter."""
        quota_memo: dict[str, bool] = {}

        def under(name: str) -> bool:
            v = quota_memo.get(name)
            if v is None:
                v = quota_memo[name] = self._under_quota(name)
            return v

        if not under(w.tenant):
            return "quota"
        if self.total_slots is not None and self._running_total >= self.total_slots:
            return "slots"
        for o in self._waiters:
            if o is not w and o.order < w.order and under(o.tenant):
                return "turn"
        return None

    # ---- load shedding ---------------------------------------------------
    def _check_shed_locked(self, tenant: str, mname: str) -> None:
        """Overload rung 1, decided BEFORE any queue state exists for
        this submission: raise the retryable ``ServerOverloaded`` when
        a queue ceiling or the EWMA drain estimate says accepting it
        would grow the backlog past what the engine can drain. The
        Retry-After hint is monotone in queue depth. Fairness note:
        the GLOBAL ceiling only sheds tenants that already hold queue
        share — a light tenant with no backlog always gets one spot in
        line, so an aggressor's storm can never shed it first."""
        queued_total = len(self._waiters)
        queued_tenant = sum(1 for w in self._waiters if w.tenant == tenant)
        why = None
        if (self.tenant_queue_limit is not None
                and queued_tenant >= self.tenant_queue_limit):
            why = "queue_tenant"
        elif (self.global_queue_limit is not None
                and queued_total >= self.global_queue_limit
                and queued_tenant > 0):
            why = "queue_global"
        elif (self.shed_drain_limit_s is not None
                and self.cost_ewma.samples > 0
                and queued_tenant > 0):
            slots = self.total_slots or max(1, self._running_total)
            drain_s = (queued_total + 1) * self.cost_ewma.value / slots
            if drain_s > self.shed_drain_limit_s:
                why = "cost"
        if why is None:
            return
        retry_after = shed_retry_after(queued_total)
        REGISTRY.counter("overload.shed").add()
        REGISTRY.counter(f"overload.shed_reason.{why}").add()
        REGISTRY.counter(f"overload.shed_tenant.{mname}").add()
        raise ServerOverloaded(
            f"tenant {tenant!r} shed at admission ({why}): "
            f"{queued_tenant} queued for this tenant, {queued_total} "
            f"queued globally, {self._running_total} running "
            f"(ewma cost {self.cost_ewma.value:.3f}s; retry after "
            f"{retry_after:.2f}s)",
            retry_after_s=retry_after,
        )

    def check_shed(self, tenant: str) -> None:
        """Synchronous shed verdict for ``tenant`` (the front-end's
        accept-time gate): raises ``ServerOverloaded`` exactly as
        ``acquire`` would, without enqueuing anything."""
        with self._cv:
            tenant = self._resolve_locked(tenant)
            self._check_shed_locked(tenant, _metric_name(tenant))

    # ---- acquire / release ----------------------------------------------
    def acquire(self, tenant: str, timeout_s: Optional[float] = None) -> str:
        """Block until ``tenant`` may start one query; returns the
        tenant name as the release token. Raises ``ResourceExhausted``
        after ``timeout_s`` in the queue."""
        t0 = time.monotonic()
        deadline = None if timeout_s is None else t0 + timeout_s
        with self._cv:
            # resolve once: past max_tenants, walk-ins share the
            # overflow lane, and ALL accounting below (state, vtime,
            # metric suffixes, the release token) uses the resolved
            # name so it stays bounded
            tenant = self._resolve_locked(tenant)
            mname = _metric_name(tenant)
            spec = self._specs[tenant]
            st = self._states[tenant]
            self._check_shed_locked(tenant, mname)
            stamp = max(st.vtime, self._vclock) + 1.0 / spec.weight
            # advance the tenant's virtual time at ENQUEUE, not
            # admission: a burst of N waiters from one tenant must
            # carry stamps v+1, v+2, ..., v+N — stamping them all v+1
            # would let the backlog admit shoulder-to-shoulder and
            # defeat exactly the overtake property the weights exist
            # for (a timed-out waiter's stamp stays spent: a tenant
            # that queues work it abandons still paid for the place it
            # held in line)
            st.vtime = stamp
            w = _Waiter(stamp, next(self._seq), tenant)
            self._waiters.append(w)
            waited = False
            try:
                while True:
                    blocker = self._blocker_of(w)
                    if blocker is None:
                        break
                    if blocker == "quota" and not w.counted_block:
                        w.counted_block = True
                        st.over_quota_blocked += 1
                        REGISTRY.counter("tenant.over_quota_blocked").add()
                        REGISTRY.counter(
                            f"tenant.over_quota_blocked.{mname}").add()
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        st.queue_timeouts += 1
                        REGISTRY.counter("tenant.queue_timeouts").add()
                        REGISTRY.counter(
                            f"tenant.queue_timeouts.{mname}").add()
                        raise ResourceExhausted(
                            f"tenant {tenant!r} admission timeout: waited "
                            f"{timeout_s}s for a fair slot "
                            f"(blocked on {blocker}; {self.describe()})"
                        )
                    waited = True
                    self._cv.wait(remaining)
            finally:
                self._waiters.remove(w)
                # whoever was behind this waiter may be admissible now
                # (including after a timeout or an async interrupt)
                self._cv.notify_all()
            st.running += 1
            st.peak_running = max(st.peak_running, st.running)
            st.admitted += 1
            self._vclock = max(self._vclock, w.stamp)
            self._running_total += 1
        queued_s = time.monotonic() - t0
        REGISTRY.counter("tenant.admitted").add()
        REGISTRY.counter(f"tenant.admitted.{mname}").add()
        if waited:
            REGISTRY.counter("tenant.queued").add()
            REGISTRY.counter(f"tenant.queued.{mname}").add()
            REGISTRY.histogram("tenant.queued_s").add(queued_s)
        return tenant

    def release(self, token: str) -> None:
        with self._cv:
            st = self._states.get(token)
            if st is not None and st.running > 0:
                st.running -= 1
                self._running_total -= 1
            self._cv.notify_all()

    @contextmanager
    def slot(self, tenant: str, timeout_s: Optional[float] = None):
        token = self.acquire(tenant, timeout_s)
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.release(token)
            # slot occupancy feeds the EWMA drain estimator (failed
            # queries included: they occupied the slot all the same)
            self.cost_ewma.update(time.monotonic() - t0)

    def kick(self) -> None:
        """Re-check blocked waiters (wired to memory-pool releases so
        byte-quota blocks clear as soon as reservations drop)."""
        with self._cv:
            self._cv.notify_all()

    def close(self) -> None:
        """Detach from the pool (idempotent): unregister the release
        listener so a retired scheduler is collectable and pool
        releases stop paying for it."""
        if (self._pool is not None and self._pool_listener is not None
                and hasattr(self._pool, "remove_release_listener")):
            self._pool.remove_release_listener(self._pool_listener)
        self._pool_listener = None

    # ---- observability ---------------------------------------------------
    def queue_depth(self) -> int:
        """Waiters currently queued for a slot — the admission-queue
        growth signal the health watchdog samples."""
        with self._cv:
            return len(self._waiters)

    def slo_overrides(self) -> "dict[str, tuple]":
        """Per-tenant SLO objective overrides for the SloTracker:
        ``{tenant: (latency_s | None, freshness_s | None)}`` for every
        registered tenant that declares at least one objective."""
        with self._cv:
            return {name: (spec.slo_latency_s, spec.slo_freshness_s)
                    for name, spec in self._specs.items()
                    if spec.slo_latency_s is not None
                    or spec.slo_freshness_s is not None}

    def describe(self) -> str:
        with self._cv:
            return (f"{self._running_total} running, "
                    f"{len(self._waiters)} queued across "
                    f"{len(self._specs)} tenants")

    def snapshot(self) -> "list[dict]":
        """One row per registered tenant (the ``system.tenants``
        backing store), internally consistent under one lock."""
        with self._cv:
            queued = {}
            for w in self._waiters:
                queued[w.tenant] = queued.get(w.tenant, 0) + 1
            rows = []
            for name, spec in sorted(self._specs.items()):
                st = self._states[name]
                rows.append({
                    "tenant": name,
                    "weight": spec.weight,
                    "max_concurrent": (-1 if spec.max_concurrent is None
                                       else spec.max_concurrent),
                    "max_bytes": (-1 if spec.max_bytes is None
                                  else spec.max_bytes),
                    "running": st.running,
                    "peak_running": st.peak_running,
                    "queued": queued.get(name, 0),
                    "admitted": st.admitted,
                    "over_quota_blocked": st.over_quota_blocked,
                    "queue_timeouts": st.queue_timeouts,
                    "reserved_bytes": self._tenant_bytes(name),
                    "vtime": st.vtime,
                })
            return rows
