"""Connector SPI — the engine/connector seam.

Reference parity: ``presto-spi`` (``ConnectorMetadata``,
``ConnectorSplitManager``, ``ConnectorSplit``, ``ConnectorPageSource``)
[SURVEY §2.1; reference tree unavailable, paths reconstructed].

TPU-first shape: a split is a deterministic key-range descriptor (pure
data, shippable to any host); a page source produces host-columnar
chunks that the engine pads into fixed-capacity device Batches. Column
pruning happens at the source (`columns=`), and connectors expose
statistics for the cost-based optimizer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Protocol, Sequence

import numpy as np

from presto_tpu.batch import Batch, Dictionary
from presto_tpu.types import DataType


@dataclass(frozen=True)
class Split:
    """A deterministic unit of scan work (a key range of a table)."""

    table: str
    chunk: int
    lo: int
    hi: int
    row_hint: int  # expected output rows (>= actual is fine)


class Connector(Protocol):
    name: str

    def tables(self) -> Sequence[str]: ...

    def schema(self, table: str) -> Mapping[str, DataType]: ...

    def dictionaries(self, table: str) -> Mapping[str, Dictionary]: ...

    def splits(self, table: str, target_splits: int) -> Sequence[Split]: ...

    def scan_numpy(
        self, split: Split, columns: Sequence[str] | None = None
    ) -> Mapping[str, np.ndarray]: ...

    def scan(
        self, split: Split, columns: Sequence[str] | None = None, capacity: int | None = None
    ) -> Batch: ...

    def row_count(self, table: str) -> int: ...


def split_valids(arrays: Mapping[str, np.ndarray]):
    """Separate ``<col>$valid`` NULL-mask companions from data columns.

    Connectors whose sources carry NULLs (tpcds fact FKs, the memory
    connector) return masks under this naming convention; the engine
    splits them here before building device Batches.
    """
    data = {c: v for c, v in arrays.items() if not c.endswith("$valid")}
    valids = {
        c[: -len("$valid")]: v for c, v in arrays.items() if c.endswith("$valid")
    }
    return data, valids


def batch_capacity(n: int, minimum: int = 1024) -> int:
    """Round a row count up to a compile-friendly capacity bucket.

    Power-of-two buckets bound the number of distinct XLA programs per
    operator chain (SURVEY §7.4 hard part #6).
    """
    cap = minimum
    while cap < n:
        cap *= 2
    return cap
