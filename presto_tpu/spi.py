"""Connector SPI — the engine/connector seam.

Reference parity: ``presto-spi`` (``ConnectorMetadata``,
``ConnectorSplitManager``, ``ConnectorSplit``, ``ConnectorPageSource``)
[SURVEY §2.1; reference tree unavailable, paths reconstructed].

TPU-first shape: a split is a deterministic key-range descriptor (pure
data, shippable to any host); a page source produces host-columnar
chunks that the engine pads into fixed-capacity device Batches. Column
pruning happens at the source (`columns=`), and connectors expose
statistics for the cost-based optimizer.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Callable, Mapping, Protocol, Sequence

import numpy as np

from presto_tpu.batch import Batch, Dictionary
from presto_tpu.types import DataType, TypeKind, narrow_physical


@dataclass(frozen=True)
class Split:
    """A deterministic unit of scan work (a key range of a table)."""

    table: str
    chunk: int
    lo: int
    hi: int
    row_hint: int  # expected output rows (>= actual is fine)


class Connector(Protocol):
    name: str

    def tables(self) -> Sequence[str]: ...

    def schema(self, table: str) -> Mapping[str, DataType]: ...

    def dictionaries(self, table: str) -> Mapping[str, Dictionary]: ...

    def splits(self, table: str, target_splits: int) -> Sequence[Split]: ...

    def scan_numpy(
        self, split: Split, columns: Sequence[str] | None = None
    ) -> Mapping[str, np.ndarray]: ...

    def scan(
        self, split: Split, columns: Sequence[str] | None = None, capacity: int | None = None
    ) -> Batch: ...

    def row_count(self, table: str) -> int: ...


def split_valids(arrays: Mapping[str, np.ndarray]):
    """Separate ``<col>$valid`` NULL-mask companions from data columns.

    Connectors whose sources carry NULLs (tpcds fact FKs, the memory
    connector) return masks under this naming convention; the engine
    splits them here before building device Batches.
    """
    data = {c: v for c, v in arrays.items() if not c.endswith("$valid")}
    valids = {
        c[: -len("$valid")]: v for c, v in arrays.items() if c.endswith("$valid")
    }
    return data, valids


@dataclass(frozen=True)
class ColumnStats:
    """The connector-statistics shape the engine consumes (duck-typed:
    the TPC-H/SSB schemas declare their own equivalents). min/max are
    LOGICAL values — decimal units, day numbers for DATE."""

    ndv: float
    min_value: float | None = None
    max_value: float | None = None
    null_fraction: float = 0.0


def narrow_enabled() -> bool:
    """Stats-driven narrow physical storage (scan columns materialized
    int8/int16/int32 when connector bounds permit). Default on;
    ``PRESTO_TPU_NARROW=0`` (mirrored by the ``narrow_storage`` session
    property) disables it for bisection."""
    v = os.environ.get("PRESTO_TPU_NARROW")
    if v is not None:
        return v.strip().lower() not in ("0", "false", "off", "no")
    return True


def stats_physical_interval(stats, dtype: DataType):
    """(lo, hi) over the PHYSICAL representation from connector
    ``ColumnStats``-shaped stats (min_value/max_value are LOGICAL:
    decimal units, day numbers for DATE), or None when unbounded.
    The one scaling rule shared by scan narrowing (here) and interval
    inference (plan/bounds._stats_interval) — the two must agree or a
    narrowed column could hold values its declared interval excludes."""
    if stats is None or stats.min_value is None or stats.max_value is None:
        return None
    if dtype.kind is TypeKind.DECIMAL:
        f = 10**dtype.scale
        return (math.floor(stats.min_value * f), math.ceil(stats.max_value * f))
    if dtype.kind in (TypeKind.INTEGER, TypeKind.BIGINT, TypeKind.DATE,
                      TypeKind.TIMESTAMP):
        return (math.floor(stats.min_value), math.ceil(stats.max_value))
    return None


def narrowed_schema(
    types: Mapping[str, DataType],
    stats_fn: Callable[[str], object],
    dictionaries: Mapping[str, Dictionary] | None = None,
) -> dict[str, DataType]:
    """Per-column physical types for a scan: each column narrowed to
    the smallest signed-int storage its declared value bounds permit
    (``types.narrow_physical``). VARCHAR narrows from its dictionary's
    code domain; numeric kinds from ``stats_fn(col)`` min/max. Columns
    without bounds — and everything when ``narrow_enabled()`` is off —
    keep canonical storage. Wrong (too-tight) stats fail LOUDLY at
    materialization (Batch.from_numpy range-checks narrowed columns),
    never by silent wraparound."""
    if not narrow_enabled():
        return dict(types)
    out = {}
    for name, t in types.items():
        d = dictionaries.get(name) if dictionaries else None
        if t.kind is TypeKind.VARCHAR and d is not None:
            out[name] = narrow_physical(t, 0, max(len(d) - 1, 0))
            continue
        iv = stats_physical_interval(stats_fn(name), t)
        out[name] = t if iv is None else narrow_physical(t, iv[0], iv[1])
    return out


def batch_capacity(n: int, minimum: int = 1024) -> int:
    """Round a row count up to a compile-friendly capacity bucket.

    Power-of-two buckets bound the number of distinct XLA programs per
    operator chain (SURVEY §7.4 hard part #6).
    """
    cap = minimum
    while cap < n:
        cap *= 2
    return cap
