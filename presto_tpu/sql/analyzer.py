"""Semantic analysis: AST -> typed logical plan.

Reference parity: ``com.facebook.presto.sql.analyzer``
(``StatementAnalyzer``, ``ExpressionAnalyzer``, ``Scope``) plus the
relational planning half of ``sql.planner`` (``RelationPlanner``,
``QueryPlanner``) and a slice of the optimizer (predicate pushdown,
greedy stats-driven join ordering standing in for ``ReorderJoins``,
subquery decorrelation standing in for ``TransformCorrelated*`` rules)
[SURVEY §2.1, §3.1; reference tree unavailable, paths reconstructed].

Subquery handling:
- EXISTS / IN-subquery  -> semi/anti joins on correlation/value keys;
- uncorrelated scalar subqueries -> ``ScalarValue`` nodes whose results
  bind ``Unbound`` expression slots at execution time;
- equality-correlated scalar aggregates (Q2/Q17/Q20 shape) ->
  decorrelated: inner query grouped by its correlation columns, joined
  back on those keys (unique build), comparison applied post-join.

Functional-dependency grouping: group-by keys covered by a table's
unique key make the remaining keys of that table "passengers" (carried
per group, not grouped) — how Q10/Q18 group by BYTES columns without
sorting byte tensors. Narrow (<=7 byte) BYTES keys group via packed
int64 surrogates (Q22's cntrycode).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

import numpy as np

from presto_tpu.exec.operators import AggSpec, SortKey
from presto_tpu.expr import Call, Expr, InputRef, Literal, Unbound, result_type, substr_fn
from presto_tpu.plan import nodes as N
from presto_tpu.plan.catalog import Catalog, TableMeta
from presto_tpu.runtime.errors import UserError
from presto_tpu.sql import ast as A
from presto_tpu.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    DataType,
    TypeKind,
    decimal,
    varchar,
)

AGG_FUNCS = {"count", "sum", "avg", "min", "max",
             "stddev_samp", "stddev", "var_samp", "variance"}

_CMP_OPS = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}
_ARITH_OPS = {"+": "add", "-": "sub", "*": "mul", "/": "div", "%": "mod"}


class AnalysisError(UserError):
    """Semantic errors — unknown tables/columns, type mismatches
    (taxonomy: USER_ERROR; ValueError ancestry preserved)."""


@dataclass(frozen=True)
class FieldRef:
    name: str  # unique internal field name (Batch column name)
    dtype: DataType
    binding: str  # relation alias/table name
    column: str  # source column name within the relation
    table: Optional[str] = None  # base table (for unique-key reasoning)


class Scope:
    def __init__(self, fields: Sequence[FieldRef]):
        self.fields = list(fields)

    def try_resolve(self, parts: tuple[str, ...]) -> FieldRef | None:
        if len(parts) == 1:
            hits = [f for f in self.fields if f.column == parts[0]]
        else:
            q, c = parts[-2], parts[-1]
            hits = [f for f in self.fields if f.binding == q and f.column == c]
        if len(hits) > 1:
            raise AnalysisError(f"ambiguous column {'.'.join(parts)}")
        return hits[0] if hits else None

    def resolve(self, parts: tuple[str, ...]) -> FieldRef:
        f = self.try_resolve(parts)
        if f is None:
            raise AnalysisError(f"column not found: {'.'.join(parts)}")
        return f

    def __add__(self, other: "Scope") -> "Scope":
        return Scope(self.fields + other.fields)


@dataclass
class Rel:
    """One relation instance in the FROM clause."""

    binding: str
    plan: N.PlanNode
    scope: Scope
    meta: Optional[TableMeta]  # None for derived tables
    group_keys: tuple[tuple[str, ...], ...] = ()  # alternative unique internal-name sets (grouped subquery)
    est_rows: float = 0.0
    filters: list[Expr] = field(default_factory=list)


def conjuncts(node: A.Node) -> list[A.Node]:
    if isinstance(node, A.BinaryOp) and node.op == "and":
        return conjuncts(node.left) + conjuncts(node.right)
    return [node]


def _ast_fields(n: A.Node):
    for f in getattr(n, "__dataclass_fields__", {}):
        yield getattr(n, f)


def collect_identifiers(n, out: list[A.Identifier]):
    if isinstance(n, A.Identifier):
        out.append(n)
        return
    if isinstance(n, (A.Exists, A.InSubquery, A.ScalarSubquery)):
        return  # bounded: inner queries resolved separately
    if isinstance(n, A.Node):
        for v in _ast_fields(n):
            collect_identifiers(v, out)
    elif isinstance(n, tuple):
        for v in n:
            collect_identifiers(v, out)


def contains_agg(n) -> bool:
    if isinstance(n, A.FunctionCall) and n.name in AGG_FUNCS and n.over is None:
        return True
    if isinstance(n, (A.Exists, A.InSubquery, A.ScalarSubquery)):
        return False
    if isinstance(n, A.Node):
        return any(contains_agg(v) for v in _ast_fields(n))
    if isinstance(n, tuple):
        return any(contains_agg(v) for v in n)
    return False


def collect_aggs(n, out: list[A.FunctionCall]):
    """Plain aggregates; window calls (``over`` set) are skipped as
    aggregates but their args/spec are searched (rank() over
    (order by sum(x)) contributes sum(x))."""
    if isinstance(n, A.FunctionCall) and n.name in AGG_FUNCS and n.over is None:
        out.append(n)
        return
    if isinstance(n, (A.Exists, A.InSubquery, A.ScalarSubquery)):
        return
    if isinstance(n, A.Node):
        for v in _ast_fields(n):
            collect_aggs(v, out)
    elif isinstance(n, tuple):
        for v in n:
            collect_aggs(v, out)


WINDOW_ONLY_FUNCS = {"rank", "dense_rank", "row_number"}


def _collect_grouping_calls(n, out: list):
    """``grouping(col)`` calls (fold to 0/1 per grouping-set branch)."""
    if isinstance(n, A.FunctionCall) and n.name == "grouping":
        if n not in out:
            out.append(n)
        return
    if isinstance(n, (A.Exists, A.InSubquery, A.ScalarSubquery)):
        return
    if isinstance(n, A.Node):
        for v in _ast_fields(n):
            _collect_grouping_calls(v, out)
    elif isinstance(n, tuple):
        for v in n:
            _collect_grouping_calls(v, out)


def collect_windows(n, out: list[A.FunctionCall]):
    """Window function calls (FunctionCall with an OVER spec). Does not
    descend into subqueries (analyzed separately) or into the window
    call itself (SQL forbids nested windows)."""
    if isinstance(n, A.FunctionCall) and n.over is not None:
        if n not in out:
            out.append(n)
        return
    if isinstance(n, (A.Exists, A.InSubquery, A.ScalarSubquery)):
        return
    if isinstance(n, A.Node):
        for v in _ast_fields(n):
            collect_windows(v, out)
    elif isinstance(n, tuple):
        for v in n:
            collect_windows(v, out)


def _resolved_refs(n, out: set[str]):
    """Collect InputRef names inside Resolved (pre-lowered) AST slots."""
    if isinstance(n, A.Resolved):
        from presto_tpu.plan.prune import expr_refs

        expr_refs(n.expr, out)
        return
    if isinstance(n, A.Node):
        for v in _ast_fields(n):
            _resolved_refs(v, out)
    elif isinstance(n, tuple):
        for v in n:
            _resolved_refs(v, out)


def _substitute_outside_aggs(n, mapping):
    """Like substitute_nodes, but leaves plain aggregate-call subtrees
    untouched — grouping-sets NULL substitution must not rewrite
    aggregate arguments. Window calls ARE entered (their partition/
    order specs reference grouping keys), and the aggregates inside
    them stay opaque via the same rule."""
    if isinstance(n, A.FunctionCall) and n.name in AGG_FUNCS and n.over is None:
        return n
    if isinstance(n, A.Node) and not isinstance(n, A.Query):
        try:
            if n in mapping:
                return mapping[n]
        except TypeError:
            pass
    if isinstance(n, A.Query) or not isinstance(n, (A.Node, tuple)):
        return n
    if isinstance(n, tuple):
        return tuple(_substitute_outside_aggs(v, mapping) for v in n)
    changes = {}
    for f in n.__dataclass_fields__:
        v = getattr(n, f)
        nv = _substitute_outside_aggs(v, mapping)
        if nv is not v:
            changes[f] = nv
    return replace(n, **changes) if changes else n


def substitute_nodes(n, mapping):
    """Structurally replace AST nodes found in ``mapping`` (by value
    equality) with their replacements; subqueries are left untouched."""
    if isinstance(n, A.Node) and not isinstance(n, A.Query):
        try:
            if n in mapping:
                return mapping[n]
        except TypeError:
            pass
    if isinstance(n, A.Query) or not isinstance(n, (A.Node, tuple)):
        return n
    if isinstance(n, tuple):
        return tuple(substitute_nodes(v, mapping) for v in n)
    changes = {}
    for f in n.__dataclass_fields__:
        v = getattr(n, f)
        nv = substitute_nodes(v, mapping)
        if nv is not v:
            changes[f] = nv
    return replace(n, **changes) if changes else n


# selectivity guesses for cardinality estimation (ReorderJoins-lite)
_SEL = {"eq": 0.05, "ne": 0.9, "lt": 0.35, "le": 0.35, "gt": 0.35, "ge": 0.35,
        "between": 0.2, "like": 0.15, "in": 0.2, "starts_with": 0.1}


def _estimate_selectivity(e: Expr) -> float:
    if isinstance(e, Call):
        if e.fn == "and":
            return _estimate_selectivity(e.args[0]) * _estimate_selectivity(e.args[1])
        if e.fn == "or":
            a = _estimate_selectivity(e.args[0])
            b = _estimate_selectivity(e.args[1])
            return min(1.0, a + b)
        if e.fn == "not":
            return max(0.05, 1 - _estimate_selectivity(e.args[0]))
        return _SEL.get(e.fn, 0.5)
    return 0.5


class Analyzer:
    def __init__(self, catalog: Catalog):
        self.catalog = catalog
        self._uniq = 0
        #: ``?`` placeholder types inferred during the last analyze()
        #: (ordinal -> DataType); Session.prepare reads them to build
        #: the prepared statement's user-slot layout
        self.param_types: dict[int, "DataType"] = {}

    # ------------------------------------------------------------------
    def fresh(self, base: str) -> str:
        self._uniq += 1
        return f"{base}${self._uniq}"

    def analyze(self, query: A.Node) -> N.PlanNode:
        # the gensym counter restarts per statement: names need only be
        # unique WITHIN one plan, and a session-lifetime counter would
        # make identical SQL produce alpha-equivalent-but-unequal plans
        # — defeating every content-keyed cache (cache/fingerprint.py).
        # The placeholder-type map restarts with it (slot ids are
        # per-statement lexical ordinals, like gensyms).
        self._uniq = 0
        self.param_types = {}
        plan, _scope = self._analyze_any(query, outer=None, ctes={})
        return plan

    def _param(self, ph: "A.Placeholder", dtype) -> Expr:
        """Type one ``?`` placeholder from its context and lower it to
        an ``expr.Param`` slot (slot id == lexical ordinal). A
        placeholder reached through two conflicting typed contexts is
        rejected — a silently coerced parameter would bind wrongly."""
        from presto_tpu.expr import Param

        if dtype.kind in (TypeKind.VARCHAR, TypeKind.BYTES):
            raise AnalysisError(
                "string parameters are not supported (dictionary "
                "encoding is a trace-time decision); inline the literal"
            )
        dtype = dtype.canonical()
        seen = self.param_types.get(ph.ordinal)
        if seen is not None and seen != dtype:
            raise AnalysisError(
                f"parameter ?{ph.ordinal + 1} used with conflicting "
                f"types {seen} and {dtype}"
            )
        self.param_types[ph.ordinal] = dtype
        return Param(dtype, ph.ordinal)

    def _analyze_any(
        self, q: A.Node, outer: Scope | None, ctes: dict
    ) -> tuple[N.PlanNode, Scope]:
        """Dispatch: plain SELECT core vs UNION chain."""
        if isinstance(q, A.SetQuery):
            return self._analyze_setquery(q, outer, ctes)
        return self._analyze_query(q, outer, ctes)

    # ------------------------------------------------------------------
    def _analyze_setquery(
        self, q: A.SetQuery, outer: Scope | None, ctes: dict
    ) -> tuple[N.PlanNode, Scope]:
        """UNION [ALL] chain -> N.Union (+ dedup Aggregate for UNION
        distinct), left-associative like the reference's SetOperation
        planning [SURVEY §2.1 planner row]. Terms are coerced to common
        column types; output names come from the first term."""
        from presto_tpu.types import common_super_type

        ctes = dict(ctes)
        for name, cq in q.ctes:
            ctes[name] = cq
        planned = [self._analyze_any(t, outer, ctes) for t in q.terms]
        first_out = planned[0][0]
        names = list(first_out.names)
        for out, _scope in planned[1:]:
            if len(out.names) != len(names):
                raise AnalysisError(
                    f"UNION terms have {len(names)} vs {len(out.names)} columns"
                )
        # unified column types across terms
        types = []
        for i in range(len(names)):
            t = planned[0][1].fields[i].dtype
            for _, scope in planned[1:]:
                t = common_super_type(t, scope.fields[i].dtype)
            types.append(t)

        # internal field names are uniquified: client names may repeat
        # (SELECT a, a FROM ...) and Batch columns are name-keyed
        internal = [self.fresh(n) for n in names]

        def as_union_input(out: N.Output, scope: Scope) -> N.PlanNode:
            exprs = []
            for i, n in enumerate(internal):
                f = scope.fields[i]
                e: Expr = InputRef(f.dtype, out.sources[i])
                exprs.append((n, self._coerce_to(e, types[i])))
            return N.Project(out.child, tuple(exprs))

        acc = as_union_input(*planned[0])
        for op, (out, scope) in zip(q.ops, planned[1:]):
            rhs = as_union_input(out, scope)
            if op in ("intersect", "except"):
                acc = self._plan_set_diff(acc, rhs, internal, types, op)
                continue
            acc = N.Union((acc, rhs))
            if op == "union":  # distinct: dedup everything so far
                acc = N.Aggregate(
                    acc,
                    tuple((n, InputRef(t, n)) for n, t in zip(internal, types)),
                    (),
                )
        plan = acc
        out_scope = Scope(
            [FieldRef(i_, t, "", n)
             for i_, n, t in zip(internal, names, types)]
        )
        if q.order_by:
            keys = []
            scalar_binds: list[N.ScalarValue] = []
            for item in q.order_by:
                e = self._order_expr(item.expr, out_scope, out_scope, None,
                                     ctes, scalar_binds, {}, {})
                keys.append(SortKey(e, item.descending, bool(item.nulls_first)))
            if q.limit is not None:
                plan = N.TopN(plan, tuple(keys), q.limit)
            else:
                plan = N.Sort(plan, tuple(keys))
            if scalar_binds:
                plan = N.BindScalars(plan, tuple(scalar_binds))
        elif q.limit is not None:
            plan = N.Limit(plan, q.limit)
        out = N.Output(plan, tuple(names), tuple(internal))
        return out, out_scope

    def _plan_set_diff(self, left, right, internal, types, op: str):
        """INTERSECT / EXCEPT (distinct) as a tagged union + grouped
        tag sums — reuses the union machinery, so mixed dictionaries
        and any groupable key types come for free (the reference plans
        these as semi joins; a tagged re-aggregation is the
        shuffle-once equivalent here):

            UNION ALL(left tagged a=1, right tagged b=1)
            GROUP BY all columns, suming the tags
            HAVING a > 0 AND (b > 0 | b = 0)
        """
        la, lb = self.fresh("seta"), self.fresh("setb")
        cols = tuple((n, InputRef(t, n)) for n, t in zip(internal, types))

        def tagged(p, a, b):
            return N.Project(
                p,
                cols + ((la, Literal(BIGINT, a)), (lb, Literal(BIGINT, b))),
            )

        u = N.Union((tagged(left, 1, 0), tagged(right, 0, 1)))
        sa, sb = self.fresh("seta"), self.fresh("setb")
        agg = N.Aggregate(
            u,
            cols,
            (
                AggSpec("sum", InputRef(BIGINT, la), sa, BIGINT),
                AggSpec("sum", InputRef(BIGINT, lb), sb, BIGINT),
            ),
        )
        in_a = Call(BOOLEAN, "gt", (InputRef(BIGINT, sa), Literal(BIGINT, 0)))
        b_zero = Literal(BIGINT, 0)
        in_b = Call(BOOLEAN, "gt", (InputRef(BIGINT, sb), b_zero))
        not_b = Call(BOOLEAN, "eq", (InputRef(BIGINT, sb), b_zero))
        cond = Call(BOOLEAN, "and", (in_a, in_b if op == "intersect" else not_b))
        return N.Project(N.Filter(agg, cond), cols)

    def _coerce_to(self, e: Expr, t) -> Expr:
        """Lift ``e`` to the union-unified type ``t`` (already a common
        super type of e.dtype per the coercion lattice)."""
        from presto_tpu.expr import rescale_decimal
        from presto_tpu.types import TypeKind as TK

        if e.dtype == t:
            return e
        if t.kind is TK.DOUBLE:
            return Call(t, "cast_double", (e,))
        if t.kind is TK.BIGINT:
            return Call(t, "cast_bigint", (e,))
        if t.kind is TK.DECIMAL:
            return Call(t, rescale_decimal(t.scale), (e,))
        if t.kind is e.dtype.kind:
            return e  # width/param variations of the same kind
        raise AnalysisError(f"cannot unify UNION column types {e.dtype} and {t}")

    # ------------------------------------------------------------------
    def _expand_grouping_sets(
        self, q: A.Query, outer, ctes: dict
    ) -> A.SetQuery | None:
        """GROUP BY ROLLUP/CUBE/GROUPING SETS -> UNION ALL of one
        grouped branch per set (the reference plans GroupingSets as a
        GroupIdNode; re-aggregation per set is the equivalent here).
        In each branch, grouping columns absent from its set become
        typed NULL literals in SELECT/HAVING, and grouping(col) folds
        to its 0/1 constant for that branch."""
        gs_items = [g for g in q.group_by if isinstance(g, A.GroupingSets)]
        if not gs_items:
            return None
        if len(gs_items) > 1:
            raise AnalysisError("multiple GROUPING SETS elements not supported")
        prefix = tuple(g for g in q.group_by if not isinstance(g, A.GroupingSets))
        gs = gs_items[0]
        all_keys: list[A.Node] = []
        for s in gs.sets:
            for k in s:
                if k not in all_keys:
                    all_keys.append(k)
        # type each grouping key against the FROM scope once, so absent
        # keys can be replaced by *typed* NULLs (the union type checker
        # needs them); this pre-analysis of FROM is throwaway
        ctes2 = dict(ctes)
        for name, cq in q.ctes:
            ctes2[name] = cq
        rels: list[Rel] = []
        edges: list[dict] = []
        if q.from_ is not None:
            self._flatten_from(q.from_, rels, edges, ctes2, outer)
        probe_scope = Scope([f for r in rels for f in r.scope.fields])
        key_null: dict[A.Node, A.Node] = {}
        for k in all_keys:
            e = self._expr(k, probe_scope, outer, ctes2, [])
            key_null[k] = A.Resolved(Literal(e.dtype, None))
        wins: list[A.FunctionCall] = []
        for it in q.select:
            collect_windows(it.expr, wins)
        if wins:
            # window functions rank/aggregate across ALL grouping sets
            # (q67: rank over the whole rollup), so they cannot run per
            # branch — hoist them above the union
            return self._expand_gs_with_windows(
                q, gs, prefix, all_keys, key_null
            )
        branches = []
        for s in gs.sets:
            grouped = set(prefix) | set(s)
            g_map: dict[A.Node, A.Node] = {}
            null_map: dict[A.Node, A.Node] = {}
            for k in all_keys:
                g_map[A.FunctionCall("grouping", (k,))] = A.NumberLit(
                    "0" if k in grouped else "1"
                )
                if k not in grouped:
                    null_map[k] = key_null[k]

            def sub(n):
                # grouping() folds anywhere; key->NULL only OUTSIDE
                # aggregate arguments (SUM(a) in a subtotal row still
                # sums the real column, standard grouping-sets
                # semantics)
                n = substitute_nodes(n, g_map)
                return _substitute_outside_aggs(n, null_map)

            branches.append(replace(
                q,
                group_by=prefix + tuple(s),
                select=tuple(sub(it) for it in q.select),
                having=sub(q.having) if q.having is not None else None,
                order_by=(),
                limit=None,
                ctes=(),
            ))
        return A.SetQuery(
            terms=tuple(branches),
            ops=("union_all",) * (len(branches) - 1),
            order_by=q.order_by,
            limit=q.limit,
            ctes=q.ctes,
        )

    def _expand_gs_with_windows(self, q: A.Query, gs, prefix, all_keys,
                                key_null) -> A.Query:
        """Grouping sets + window functions: per-branch grouped inner
        queries (no windows) UNION ALL'd, with the windows applied in an
        outer query over the union — window partitions/orders see every
        grouping set at once, matching the reference's GroupIdNode →
        WindowNode plan order [SURVEY §2.1 planner row].

        Inner branches emit: each grouping key under its terminal
        column name, every distinct plain-aggregate subtree as
        ``__agg{i}``, and every ``grouping(...)`` call folded to its
        per-branch constant as ``__grp{i}``. The outer query is the
        original select/order/limit with those subtrees replaced by
        references."""
        key_items: list[A.Node] = []
        for k in tuple(prefix) + tuple(all_keys):
            if k not in key_items:
                key_items.append(k)
        for k in key_items:
            if not isinstance(k, A.Identifier):
                raise AnalysisError(
                    "window functions over grouping sets require "
                    "identifier grouping keys"
                )
        key_map = {k: A.Identifier((k.parts[-1],)) for k in key_items}

        aggs: list[A.FunctionCall] = []
        grps: list[A.FunctionCall] = []
        for it in q.select:
            collect_aggs(it.expr, aggs)
            _collect_grouping_calls(it.expr, grps)
        for oi in q.order_by:
            collect_aggs(oi.expr, aggs)
            _collect_grouping_calls(oi.expr, grps)
        uniq_aggs: list[A.FunctionCall] = []
        for a in aggs:
            if a not in uniq_aggs:
                uniq_aggs.append(a)
        uniq_grps: list[A.FunctionCall] = []
        for g in grps:
            if g not in uniq_grps:
                uniq_grps.append(g)
        agg_map = {a: A.Identifier((f"__agg{i}",))
                   for i, a in enumerate(uniq_aggs)}
        grp_map = {g: A.Identifier((f"__grp{i}",))
                   for i, g in enumerate(uniq_grps)}

        branches = []
        for s in gs.sets:
            grouped = set(prefix) | set(s)
            inner_items = []
            for k in key_items:
                e = k if k in grouped else key_null[k]
                inner_items.append(A.SelectItem(e, k.parts[-1]))
            for a, ref in agg_map.items():
                inner_items.append(A.SelectItem(a, ref.parts[0]))
            for g, ref in grp_map.items():
                folded = A.NumberLit("0" if g.args[0] in grouped else "1")
                inner_items.append(A.SelectItem(folded, ref.parts[0]))
            g_fold = {g: A.NumberLit("0" if g.args[0] in grouped else "1")
                      for g in uniq_grps}
            having = q.having
            if having is not None:
                having = _substitute_outside_aggs(
                    substitute_nodes(having, g_fold),
                    {k: key_null[k] for k in all_keys if k not in grouped},
                )
            branches.append(replace(
                q, select=tuple(inner_items),
                group_by=tuple(prefix) + tuple(s),
                having=having, order_by=(), limit=None, ctes=(),
            ))

        def rewrite(n):
            return substitute_nodes(
                substitute_nodes(substitute_nodes(n, agg_map), grp_map),
                key_map,
            )

        outer_select = tuple(
            A.SelectItem(rewrite(it.expr), it.alias) for it in q.select
        )
        outer_order = tuple(
            replace(oi, expr=rewrite(oi.expr)) for oi in q.order_by
        )
        inner = A.SetQuery(
            terms=tuple(branches), ops=("union_all",) * (len(branches) - 1)
        )
        return A.Query(
            select=outer_select,
            from_=A.SubqueryRelation(inner, self.fresh("gsw")),
            order_by=outer_order, limit=q.limit, ctes=q.ctes,
        )

    # ------------------------------------------------------------------
    def _analyze_query(
        self, q: A.Query, outer: Scope | None, ctes: dict[str, A.Query]
    ) -> tuple[N.PlanNode, Scope]:
        expanded = self._expand_grouping_sets(q, outer, ctes)
        if isinstance(expanded, A.Query):
            return self._analyze_query(expanded, outer, ctes)
        if expanded is not None:
            return self._analyze_setquery(expanded, outer, ctes)
        ctes = dict(ctes)
        for name, cq in q.ctes:
            ctes[name] = cq

        # ---- FROM: relations + join graph -----------------------------
        rels: list[Rel] = []
        edges: list[dict] = []  # {a, b, akeys, bkeys, kind, residual}
        if q.from_ is not None:
            self._flatten_from(q.from_, rels, edges, ctes, outer)
        scope = Scope([f for r in rels for f in r.scope.fields])

        # ---- WHERE classification -------------------------------------
        residual: list[A.Node] = []
        sub_preds: list[A.Node] = []
        corr_scalar: list[tuple[A.Node, str]] = []
        scalar_binds: list[N.ScalarValue] = []
        if q.where is not None:
            for c in conjuncts(q.where):
                self._classify_conjunct(
                    c, rels, edges, residual, sub_preds, scope, outer, ctes
                )

        # ---- order the joins ------------------------------------------
        plan = self._build_join_tree(rels, edges, scope)

        # residual filters (multi-relation, non-equi)
        for c in residual:
            e = self._expr(c, scope, outer, ctes, scalar_binds)
            plan = N.Filter(plan, e)

        # semi/anti joins & correlated scalar rewrites from WHERE
        for c in sub_preds:
            plan = self._apply_subquery_pred(c, plan, scope, outer, ctes, scalar_binds)

        # ---- aggregation ----------------------------------------------
        has_agg = (
            bool(q.group_by)
            or any(contains_agg(it.expr) for it in q.select)
            or (q.having is not None and contains_agg(q.having))
        )
        if has_agg:
            plan, scope, agg_map, key_map = self._plan_aggregate(
                q, plan, scope, outer, ctes, scalar_binds
            )
        else:
            agg_map, key_map = {}, {}
            if q.having is not None:
                raise AnalysisError("HAVING without aggregation")

        # ---- HAVING ----------------------------------------------------
        if q.having is not None:
            e = self._expr(q.having, scope, outer, ctes, scalar_binds,
                           agg_map=agg_map, key_map=key_map)
            plan = N.Filter(plan, e)

        # ---- window functions (evaluated over the grouped/filtered
        # rows, before the SELECT projection) ---------------------------
        win_calls: list[A.FunctionCall] = []
        for it in q.select:
            collect_windows(it.expr, win_calls)
        order_only_wins: list[A.FunctionCall] = []
        for ob in q.order_by:
            collect_windows(ob.expr, order_only_wins)
        order_only_wins = [w for w in order_only_wins if w not in win_calls]
        win_fields: list[N.Field] = []
        if win_calls or order_only_wins:
            plan, win_map, win_fields = self._plan_windows(
                win_calls + order_only_wins, plan, scope, outer, ctes,
                scalar_binds, agg_map, key_map,
            )
            mapping = {w: A.Resolved(e) for w, e in win_map.items()}
            q = replace(
                q,
                select=tuple(substitute_nodes(it, mapping) for it in q.select),
                order_by=tuple(substitute_nodes(ob, mapping) for ob in q.order_by),
            )

        # ---- SELECT projection ----------------------------------------
        out_names: list[str] = []
        out_exprs: list[tuple[str, Expr]] = []
        for i, item in enumerate(q.select):
            if isinstance(item.expr, A.Star):
                for f in scope.fields:
                    out_names.append(f.column)
                    out_exprs.append((f.column, InputRef(f.dtype, f.name)))
                continue
            e = self._expr(item.expr, scope, outer, ctes, scalar_binds,
                           agg_map=agg_map, key_map=key_map)
            name = item.alias or self._default_name(item.expr, i)
            out_names.append(name)
            out_exprs.append((name, e))
        # window outputs consumed only by ORDER BY ride the projection
        # as hidden columns (pruned away when unreferenced); they are
        # not client-visible fields
        hidden: list[tuple[str, Expr]] = []
        if win_fields and q.order_by:
            produced = {n for n, _ in out_exprs}
            ob_refs: set[str] = set()
            for ob in q.order_by:
                _resolved_refs(ob.expr, ob_refs)
            hidden = [
                (f.name, InputRef(f.dtype, f.name))
                for f in win_fields
                if f.name in ob_refs and f.name not in produced
            ]
            if q.distinct and hidden:
                raise AnalysisError(
                    "DISTINCT with window expressions repeated in ORDER BY "
                    "is not supported; order by the select alias instead"
                )
        plan = N.Project(plan, tuple(out_exprs) + tuple(hidden))
        out_scope = Scope(
            [FieldRef(n, e.dtype, "", n) for n, e in out_exprs]
        )

        # ---- DISTINCT --------------------------------------------------
        if q.distinct:
            plan = N.Aggregate(
                plan,
                tuple((f.name, InputRef(f.dtype, f.name)) for f in out_scope.fields),
                (),
            )

        # ---- ORDER BY / LIMIT -----------------------------------------
        if q.order_by:
            keys = []
            src_map = {
                e.name: n for n, e in out_exprs if isinstance(e, InputRef)
            }
            for item in q.order_by:
                e = self._order_expr(item.expr, out_scope, scope, outer, ctes,
                                     scalar_binds, agg_map, key_map,
                                     src_map=src_map)
                keys.append(SortKey(e, item.descending, bool(item.nulls_first)))
            if q.limit is not None:
                plan = N.TopN(plan, tuple(keys), q.limit)
            else:
                plan = N.Sort(plan, tuple(keys))
        elif q.limit is not None:
            plan = N.Limit(plan, q.limit)

        # scalar-value bindings wrap the plan (executed first)
        if scalar_binds:
            plan = N.BindScalars(plan, tuple(scalar_binds))

        out = N.Output(plan, tuple(out_names), tuple(n for n, _ in out_exprs))
        return out, out_scope

    # ------------------------------------------------------------------
    def _default_name(self, e: A.Node, i: int) -> str:
        if isinstance(e, A.Identifier):
            return e.parts[-1]
        return f"_col{i}"

    # ------------------------------------------------------------------
    # FROM flattening
    # ------------------------------------------------------------------
    def _flatten_from(self, rel: A.Node, rels, edges, ctes, outer):
        if isinstance(rel, A.Table):
            binding = rel.alias or rel.name
            if rel.name in ctes:
                plan, sub_scope = self._analyze_any(ctes[rel.name], None, ctes)
                self._add_derived(rels, binding, plan, sub_scope)
                return
            meta = self.catalog.resolve(rel.name)
            fields = []
            cols = []
            types = []
            # internal names must be unique ACROSS the FROM clause: an
            # unaliased table keeps its plain column names only while
            # they don't collide with an earlier relation's (two
            # unaliased tables sharing a column name would otherwise
            # collide in the joined Batch's column dict)
            used = {f.name for r in rels for f in r.scope.fields}
            for cname, t in meta.schema.items():
                if rel.alias or cname in used:
                    iname = self.fresh(f"{binding}.{cname}")
                else:
                    iname = cname
                fields.append(FieldRef(iname, t, binding, cname, meta.table))
                cols.append((iname, cname))
                types.append(t)
            scan = N.TableScan(meta.connector_name, meta.table, tuple(cols), tuple(types))
            rels.append(Rel(binding, scan, Scope(fields), meta,
                            est_rows=float(meta.row_count)))
            return
        if isinstance(rel, A.SubqueryRelation):
            binding = rel.alias or self.fresh("subq")
            plan, sub_scope = self._analyze_any(rel.query, None, ctes)
            self._add_derived(rels, binding, plan, sub_scope)
            return
        if isinstance(rel, A.Join):
            l0 = len(rels)
            self._flatten_from(rel.left, rels, edges, ctes, outer)
            nleft = len(rels)
            self._flatten_from(rel.right, rels, edges, ctes, outer)
            if rel.kind == "cross":
                return
            # ON condition -> equi keys + residual, between the two sides
            left_scope = Scope([f for r in rels[:nleft] for f in r.scope.fields])
            right_scope = Scope([f for r in rels[nleft:] for f in r.scope.fields])
            akeys, bkeys, res = [], [], []
            for c in conjuncts(rel.on) if rel.on is not None else []:
                pair = self._equi_pair(c, left_scope, right_scope)
                if pair is not None:
                    akeys.append(pair[0])
                    bkeys.append(pair[1])
                else:
                    res.append(c)
            kind = rel.kind
            # relations on the NULL-extended side(s) of an outer join:
            # WHERE conjuncts over them must stay post-join filters —
            # pushing them into the scan would change outer-join
            # semantics (q78's `where wr_order_number is null`)
            nullable: set[int] = set()
            if kind in ("left", "full"):
                nullable |= set(range(nleft, len(rels)))
            if kind in ("right", "full"):
                nullable |= set(range(l0, nleft))
            if kind == "right":
                # A RIGHT JOIN B == B LEFT JOIN A: swap the key
                # orientation (akeys are spine-side) and record a left
                # join — the join-tree builder then forces the spine to
                # the preserved (original right) side.
                akeys, bkeys = bkeys, akeys
                kind = "left"
            edges.append(
                dict(kind=kind, left=nleft, akeys=akeys, bkeys=bkeys,
                     residual=res, nullable=nullable)
            )
            return
        raise AnalysisError(f"unsupported relation {type(rel).__name__}")

    def _agg_key_outputs(self, node) -> tuple[tuple[str, ...], ...]:
        """Alternative output-name sets (at ``node``'s level) each
        unique per row of an Aggregate below — possibly through Project
        renames / Filters. () when not provably grouped-unique."""
        mappings: list[dict[str, str]] = []  # out name -> in name
        while True:
            if isinstance(node, N.Filter):
                node = node.child
                continue
            if isinstance(node, N.Project):
                mappings.append({
                    n2: e.name for n2, e in node.exprs
                    if isinstance(e, InputRef)
                })
                node = node.child
                continue
            break
        if not isinstance(node, N.Aggregate):
            return ()
        sets = list(node.unique_sets) or [tuple(n for n, _ in node.keys)]
        out: list[tuple[str, ...]] = []
        for names in sets:
            names = list(names)
            ok = True
            for m in reversed(mappings):
                inv: dict[str, str] = {}
                for out_n, in_n in m.items():
                    inv.setdefault(in_n, out_n)
                mapped = [inv.get(n) for n in names]
                if any(n is None for n in mapped):
                    ok = False  # a member is not exposed upward
                    break
                names = mapped
            if ok:
                out.append(tuple(names))
        return tuple(out)

    def _add_derived(self, rels, binding, plan, sub_scope):
        group_keys = self._agg_key_outputs(
            plan.child if isinstance(plan, N.Output) else plan
        )
        # strip Output: keep the projected child, re-projected to FRESH
        # internal names — two derived tables exposing the same client
        # column name (q65's sb/sc both expose ss_store_sk) must not
        # collide in the join's field namespace
        inner = plan.child if isinstance(plan, N.Output) else plan
        if isinstance(plan, N.Output):
            exprs = []
            fields = []
            iname_of = {}
            smap = {f.name: f for f in inner.fields}
            for n, s in zip(plan.names, plan.sources):
                iname = self.fresh(f"{binding}.{n}")
                exprs.append((iname, InputRef(smap[s].dtype, s)))
                fields.append(FieldRef(iname, smap[s].dtype, binding, n, None))
                iname_of.setdefault(s, iname)
            inner = N.Project(inner, tuple(exprs))
            if group_keys:
                group_keys = tuple(
                    tuple(iname_of.get(k, k) for k in s) for s in group_keys
                )
        else:
            fields = [
                FieldRef(f.name, f.dtype, binding, f.name, None)
                for f in plan.fields
            ]
        rels.append(Rel(binding, inner, Scope(fields), None,
                        group_keys=group_keys, est_rows=1e5))

    # ------------------------------------------------------------------
    # WHERE conjunct classification
    # ------------------------------------------------------------------
    @staticmethod
    def _rel_has(r, f: FieldRef) -> bool:
        """Does rel ``r`` own field ``f``? Matched on (name, binding) —
        name alone is ambiguous when two unaliased tables expose the
        same column name (t1.k = t2.k must not resolve both sides to
        the first rel and silently degenerate to a cross join)."""
        return any(
            sf.name == f.name and sf.binding == f.binding
            for sf in r.scope.fields
        )

    def _rel_of(self, ident_fields: list[FieldRef], rels) -> int | None:
        owners = set()
        for f in ident_fields:
            for i, r in enumerate(rels):
                if self._rel_has(r, f):
                    owners.add(i)
        if len(owners) == 1:
            return owners.pop()
        return None

    def _classify_conjunct(self, c, rels, edges, residual, sub_preds, scope, outer, ctes):
        # subquery predicates go to the dedicated path
        if self._contains_subquery(c):
            sub_preds.append(c)
            return
        ids: list[A.Identifier] = []
        collect_identifiers(c, ids)
        refs = []
        unresolved_outer = False
        for i in ids:
            f = scope.try_resolve(i.parts) if i.parts != ("null",) else None
            if f is None and i.parts != ("null",):
                unresolved_outer = True
            elif f is not None:
                refs.append(f)
        if unresolved_outer:
            residual.append(c)
            return
        nullable = set()
        for e2 in edges:
            nullable |= e2.get("nullable", set())
        # equi-join conjunct?
        pair = self._equi_pair_any(c, rels, scope)
        if pair is not None:
            a, b, ae, be = pair
            if a in nullable or b in nullable:
                # a WHERE equality over a NULL-extended side of an
                # outer join must filter AFTER the join (it drops the
                # null-extended rows); merging it into the outer join
                # as a key would retain them
                residual.append(c)
                return
            edges.append(dict(kind="inner", pair=(a, b), akeys=[ae], bkeys=[be],
                              residual=[]))
            return
        owner = self._rel_of(refs, rels)
        if owner is not None:
            if owner in nullable:
                # nullable-side predicate: SQL applies it AFTER the
                # outer join (it sees the null-extended rows)
                residual.append(c)
                return
            e = self._expr(c, rels[owner].scope, outer, ctes, [])
            rels[owner].filters.append(e)
            rels[owner].est_rows *= _estimate_selectivity(e)
            return
        # OR-of-ANDs (Q19 shape): factor equi conjuncts common to every
        # branch into join edges; the OR itself stays as a residual.
        if isinstance(c, A.BinaryOp) and c.op == "or":
            branches = self._disjuncts(c)
            sets = [conjuncts(b) for b in branches]
            common = [x for x in sets[0] if all(x in s for s in sets[1:])]
            for cc in common:
                pair = self._equi_pair_any(cc, rels, scope)
                if pair is not None:
                    a, b, ae, be = pair
                    if a in nullable or b in nullable:
                        continue  # same outer-join guard as above
                    edges.append(dict(kind="inner", pair=(a, b),
                                      akeys=[ae], bkeys=[be], residual=[]))
        residual.append(c)

    def _disjuncts(self, n: A.Node) -> list[A.Node]:
        if isinstance(n, A.BinaryOp) and n.op == "or":
            return self._disjuncts(n.left) + self._disjuncts(n.right)
        return [n]

    def _contains_subquery(self, n) -> bool:
        if isinstance(n, (A.Exists, A.InSubquery, A.ScalarSubquery)):
            return True
        if isinstance(n, A.Node):
            return any(self._contains_subquery(v) for v in _ast_fields(n))
        if isinstance(n, tuple):
            return any(self._contains_subquery(v) for v in n)
        return False

    def _equi_pair(self, c, left_scope: Scope, right_scope: Scope):
        """col = col across two scopes -> (left_field, right_field)."""
        if not (isinstance(c, A.BinaryOp) and c.op == "="):
            return None
        if not (isinstance(c.left, A.Identifier) and isinstance(c.right, A.Identifier)):
            return None
        lf = left_scope.try_resolve(c.left.parts)
        rf = right_scope.try_resolve(c.right.parts)
        if lf is not None and rf is not None:
            return lf, rf
        lf2 = left_scope.try_resolve(c.right.parts)
        rf2 = right_scope.try_resolve(c.left.parts)
        if lf2 is not None and rf2 is not None:
            return lf2, rf2
        return None

    def _equi_pair_any(self, c, rels, scope):
        if not (isinstance(c, A.BinaryOp) and c.op == "="):
            return None
        if not (isinstance(c.left, A.Identifier) and isinstance(c.right, A.Identifier)):
            return None
        lf = scope.try_resolve(c.left.parts)
        rf = scope.try_resolve(c.right.parts)
        if lf is None or rf is None:
            return None
        ra = self._owner_index(rels, lf)
        rb = self._owner_index(rels, rf)
        if ra is None or rb is None or ra == rb:
            return None
        return ra, rb, lf, rf

    def _owner_index(self, rels, f: FieldRef) -> int | None:
        for i, r in enumerate(rels):
            if self._rel_has(r, f):
                return i
        return None

    # ------------------------------------------------------------------
    # join tree construction (greedy, stats-driven)
    # ------------------------------------------------------------------
    def _build_join_tree(self, rels: list[Rel], edges: list[dict], scope: Scope):
        if not rels:
            # FROM-less SELECT: one literal row (reference: ValuesNode)
            return N.Values()
        # apply pushdown filters
        plans: list[N.PlanNode] = []
        for r in rels:
            p = r.plan
            for e in r.filters:
                p = N.Filter(p, e)
            plans.append(p)
        if len(rels) == 1:
            return plans[0]

        # normalize edges: explicit-ON edges have 'left' marker; WHERE
        # edges have 'pair'
        norm = []
        for e in edges:
            if "pair" in e:
                norm.append(e)
            else:
                # explicit join: between rel index e['left']-1 side...
                # find owners of its key fields
                a = self._owner_index(rels, e["akeys"][0]) if e["akeys"] else None
                b = self._owner_index(rels, e["bkeys"][0]) if e["bkeys"] else None
                if a is None or b is None:
                    raise AnalysisError("unsupported join condition")
                norm.append(dict(kind=e["kind"], pair=(a, b),
                                 akeys=e["akeys"], bkeys=e["bkeys"],
                                 residual=e["residual"]))
        edges = norm

        # pick the spine: preserved side of a LEFT/FULL join wins, else
        # largest (for FULL the probe side is the spine; the build side's
        # unmatched rows are emitted by the kernel's tail pass)
        forced = [e["pair"][0] for e in edges if e["kind"] in ("left", "full")]
        if forced:
            spine = forced[0]
        else:
            spine = max(range(len(rels)), key=lambda i: rels[i].est_rows)

        joined = {spine}
        plan = plans[spine]
        cur_fields = list(rels[spine].scope.fields)
        remaining = set(range(len(rels))) - joined
        pending_edges = list(edges)

        while remaining:
            # candidate edges connecting joined <-> one unjoined rel
            best = None
            for e in pending_edges:
                a, b = e["pair"]
                if (a in joined) == (b in joined):
                    continue
                inner_rel = b if a in joined else a
                key = rels[inner_rel].est_rows
                if best is None or key < best[0]:
                    best = (key, e, inner_rel)
            if best is None:
                # cartesian product: no edge reaches the joined set
                # (TPC-DS q88/q90 cross-join single-row derived counts).
                # Join on a constant key — every probe row matches every
                # build row; smallest relation first bounds the blowup.
                bidx = min(remaining, key=lambda i: rels[i].est_rows)
                build_rel = rels[bidx]
                one = Literal(BIGINT, 1)
                plan = N.Join(
                    plan, plans[bidx], "inner", (one,), (one,),
                    False,
                    tuple(f.name for f in build_rel.scope.fields),
                )
                joined.add(bidx)
                remaining.discard(bidx)
                cur_fields += build_rel.scope.fields
                continue
            _, e, bidx = best
            a, b = e["pair"]
            # merge every edge between `joined` and bidx into one
            # multi-key join
            akeys: list[FieldRef] = []
            bkeys: list[FieldRef] = []
            kind = "inner"
            used = []
            on_residual: list[A.Node] = []
            for e2 in pending_edges:
                p2 = e2["pair"]
                if set(p2) <= joined | {bidx} and bidx in p2:
                    used.append(e2)
                    if e2["kind"] in ("left", "full"):
                        kind = e2["kind"]
                    on_residual.extend(e2.get("residual", ()))
                    for ak, bk in zip(e2["akeys"], e2["bkeys"]):
                        # orient: probe key in joined set, build key in bidx
                        if self._owner_index(rels, ak) == bidx:
                            ak, bk = bk, ak
                        akeys.append(ak)
                        bkeys.append(bk)
            for u in used:
                pending_edges.remove(u)
            if not akeys:
                raise AnalysisError("join without equi keys")
            # ON-clause residual conjuncts: build-side-only ones filter
            # the build input (required for LEFT semantics); others are
            # legal as post-join filters only for INNER joins.
            post_join: list[A.Node] = []
            for c in on_residual:
                ids: list[A.Identifier] = []
                collect_identifiers(c, ids)
                bscope = rels[bidx].scope
                if all(bscope.try_resolve(i.parts) is not None for i in ids):
                    plans[bidx] = N.Filter(
                        plans[bidx], self._expr(c, bscope, None, {}, [])
                    )
                elif kind == "inner":
                    post_join.append(c)
                else:
                    raise AnalysisError(
                        "outer-join ON condition spanning both sides is "
                        "not supported"
                    )
            build_rel = rels[bidx]
            unique = self._is_unique_key(build_rel, bkeys)
            plan = N.Join(
                plan,
                plans[bidx],
                kind,
                tuple(InputRef(k.dtype, k.name) for k in akeys),
                tuple(InputRef(k.dtype, k.name) for k in bkeys),
                unique,
                tuple(f.name for f in build_rel.scope.fields
                      if f.name not in {k.name for k in bkeys}) +
                tuple(k.name for k in bkeys),
            )
            joined.add(bidx)
            remaining.discard(bidx)
            cur_fields += build_rel.scope.fields
            for c in post_join:
                plan = N.Filter(plan, self._expr(c, Scope(cur_fields), None, {}, []))
        return plan

    def _is_unique_key(self, rel: Rel, keys: list[FieldRef]) -> bool:
        # meta unique_keys name SOURCE columns (FieldRef.column);
        # derived-rel group_keys holds ALTERNATIVE unique sets of
        # INTERNAL field names (FieldRef.name) from _agg_key_outputs
        colset = {k.column for k in keys} | {k.name for k in keys}
        # a pushdown equality-literal filter pins a column to one value,
        # so it counts toward uniqueness (q74: each year_total instance
        # is filtered to one sale_type and one year)
        for e in rel.filters:
            if isinstance(e, Call) and e.fn == "eq":
                a, b = e.args
                if isinstance(a, InputRef) and isinstance(b, Literal):
                    colset.add(a.name)
                elif isinstance(b, InputRef) and isinstance(a, Literal):
                    colset.add(b.name)
        if rel.meta is not None:
            return any(set(uk) <= colset for uk in rel.meta.unique_keys)
        return any(set(s) <= colset for s in rel.group_keys)

    # ------------------------------------------------------------------
    # subquery predicates
    # ------------------------------------------------------------------
    def _as_plain_query(self, q: A.Node) -> A.Query:
        """Wrap a SetQuery as SELECT * FROM (<union>) so the subquery
        rewrite machinery (which pattern-matches Query fields) can
        consume UNIONs in IN/EXISTS/scalar positions. Correlated
        references inside the union fail resolution cleanly (outer
        scope is not threaded through the wrapper)."""
        if isinstance(q, A.SetQuery):
            return A.Query(
                select=(A.SelectItem(A.Star(), None),),
                from_=A.SubqueryRelation(q, self.fresh("u")),
            )
        return q

    def _apply_subquery_pred(self, c, plan, scope, outer, ctes, scalar_binds):
        # EXISTS / NOT EXISTS
        node = c
        negated = False
        while isinstance(node, A.UnaryOp) and node.op == "not":
            negated = not negated
            node = node.operand
        if isinstance(node, A.Exists):
            return self._plan_exists(
                self._as_plain_query(node.query), negated != node.negated,
                plan, scope, ctes,
            )
        if isinstance(node, A.InSubquery):
            value = self._expr(node.value, scope, outer, ctes, scalar_binds)
            sub_plan, sub_scope = self._analyze_query(
                self._as_plain_query(node.query), None, ctes
            )
            inner = sub_plan.child if isinstance(sub_plan, N.Output) else sub_plan
            key_name = (
                sub_plan.sources[0] if isinstance(sub_plan, N.Output)
                else inner.field_names()[0]
            )
            kf = {f.name: f for f in inner.fields}[key_name]
            return N.SemiJoin(
                plan, inner, (value,), (InputRef(kf.dtype, kf.name),),
                negated != node.negated,
            )
        if isinstance(node, A.BinaryOp) and node.op in _CMP_OPS:
            # comparison against a scalar subquery
            sub = None
            other = None
            flip = False
            if isinstance(node.right, A.ScalarSubquery):
                sub, other = node.right, node.left
            elif isinstance(node.left, A.ScalarSubquery):
                sub, other, flip = node.left, node.right, True
            if sub is not None:
                return self._plan_scalar_compare(
                    node.op, other, sub.query, negated, flip, plan, scope, outer,
                    ctes, scalar_binds,
                )
        if isinstance(node, A.Between) and not negated and not node.negated:
            # BETWEEN with scalar-subquery bounds (q54's month window):
            # split into two range conjuncts and plan each
            for op_, bound in ((">=", node.low), ("<=", node.high)):
                c2 = A.BinaryOp(op_, node.value, bound)
                if self._contains_subquery(c2):
                    plan = self._apply_subquery_pred(
                        c2, plan, scope, outer, ctes, scalar_binds
                    )
                else:
                    plan = N.Filter(
                        plan, self._expr(c2, scope, outer, ctes, scalar_binds)
                    )
            return plan
        if isinstance(node, A.BinaryOp) and node.op in ("or", "and") and not negated:
            # boolean combination containing EXISTS leaves (TPC-DS
            # q10/q35 `exists(web) or exists(catalog)`): mark-join
            # rewrite — each EXISTS becomes a boolean mark column via a
            # dedup'd LEFT join (reference: MarkDistinct/mark joins in
            # the subquery planner [SURVEY §2.1 operator row])
            return self._apply_mark_bool(node, plan, scope, outer, ctes,
                                         scalar_binds)
        raise AnalysisError(f"unsupported subquery predicate: {type(node).__name__}")

    def _apply_mark_bool(self, c, plan, scope, outer, ctes, scalar_binds):
        """Rewrite a boolean expression whose subquery leaves are all
        positive equality-correlated EXISTS: each leaf adds a mark
        column to ``plan``; the expression is then a plain filter."""
        added: list[FieldRef] = []

        def walk(n):
            nonlocal plan
            if isinstance(n, A.Exists):
                if n.negated:
                    raise AnalysisError(
                        "NOT EXISTS inside OR predicates is not supported"
                    )
                plan, mark = self._plan_exists_mark(
                    self._as_plain_query(n.query), plan, scope, ctes
                )
                added.append(mark)
                return A.Identifier((mark.column,))
            if isinstance(n, (A.InSubquery, A.ScalarSubquery)):
                raise AnalysisError(
                    "only EXISTS is supported inside OR predicates"
                )
            if isinstance(n, A.BinaryOp):
                return A.BinaryOp(n.op, walk(n.left), walk(n.right))
            if isinstance(n, A.UnaryOp):
                return A.UnaryOp(n.op, walk(n.operand))
            return n

        new_ast = walk(c)
        ext = Scope(list(scope.fields) + added)
        pred = self._expr(new_ast, ext, outer, ctes, scalar_binds)
        return N.Filter(plan, pred)

    def _plan_exists_mark(self, sub_q: A.Query, plan, scope, ctes):
        """Plan one EXISTS as a mark: dedup the inner correlation keys
        (GROUP BY -> unique build), LEFT-join them onto ``plan``, and
        project a BOOLEAN mark = key-matched. Returns (plan, mark_field)."""
        probe = self._inner_scope_probe(sub_q, ctes)
        new_where, corr, neq = self._split_correlation(sub_q, probe, scope, ctes)
        if not corr or neq:
            raise AnalysisError(
                "EXISTS inside OR must be equality-correlated"
            )
        inner_cols = tuple(A.Identifier(ip) for _, ip in corr)
        rewritten = A.Query(
            select=tuple(A.SelectItem(ic, None) for ic in inner_cols),
            from_=sub_q.from_, where=new_where, group_by=inner_cols,
        )
        sub_plan, _ = self._analyze_query(rewritten, None, ctes)
        inner = sub_plan.child if isinstance(sub_plan, N.Output) else sub_plan
        sources = (sub_plan.sources if isinstance(sub_plan, N.Output)
                   else inner.field_names())
        imap = {f.name: f for f in inner.fields}
        carried = self.fresh("mark")
        ren = N.Project(
            inner,
            tuple(
                (carried if f.name == sources[0] else f.name,
                 InputRef(f.dtype, f.name))
                for f in inner.fields
            ),
        )
        right_keys = tuple(
            InputRef(imap[s].dtype, carried if i == 0 else s)
            for i, s in enumerate(sources)
        )
        left_keys = tuple(
            InputRef(scope.resolve(op_).dtype, scope.resolve(op_).name)
            for op_, _ in corr
        )
        joined = N.Join(plan, ren, "left", left_keys, right_keys, True,
                        (carried,))
        mark_name = self.fresh("markb")
        kd = imap[sources[0]].dtype
        exprs = tuple(
            (f.name, InputRef(f.dtype, f.name))
            for f in joined.fields if f.name != carried
        ) + ((mark_name, Call(BOOLEAN, "is_not_null",
                              (InputRef(kd, carried),))),)
        return (
            N.Project(joined, exprs),
            FieldRef(mark_name, BOOLEAN, "", mark_name, None),
        )

    def _split_correlation(self, q: A.Query, inner_scope_probe, outer_scope: Scope,
                           ctes):
        """Analyze a possibly-correlated subquery: returns
        (decorrelated_query_where, corr_pairs, neq_pairs) where each
        pair list holds (outer_parts, inner_parts) from ``=`` / ``<>``
        conjuncts correlating inner and outer columns."""
        corr = []
        neq = []
        keep = []
        if q.where is not None:
            for c in conjuncts(q.where):
                if (isinstance(c, A.BinaryOp) and c.op in ("=", "<>")
                        and isinstance(c.left, A.Identifier)
                        and isinstance(c.right, A.Identifier)):
                    sink = corr if c.op == "=" else neq
                    li = inner_scope_probe(c.left.parts)
                    ri = inner_scope_probe(c.right.parts)
                    lo = outer_scope.try_resolve(c.left.parts) if outer_scope else None
                    ro = outer_scope.try_resolve(c.right.parts) if outer_scope else None
                    if li is None and lo is not None and ri is not None:
                        sink.append((c.left.parts, c.right.parts))
                        continue
                    if ri is None and ro is not None and li is not None:
                        sink.append((c.right.parts, c.left.parts))
                        continue
                keep.append(c)
        new_where = None
        for c in keep:
            new_where = c if new_where is None else A.BinaryOp("and", new_where, c)
        return new_where, corr, neq

    def _inner_scope_probe(self, q: A.Query, ctes):
        """Build a resolver over the subquery's own FROM scope."""
        rels: list[Rel] = []
        edges: list[dict] = []
        if q.from_ is not None:
            self._flatten_from(q.from_, rels, edges, ctes, None)
        sc = Scope([f for r in rels for f in r.scope.fields])
        return lambda parts: sc.try_resolve(parts)

    def _plan_exists(self, sub_q: A.Query, negated: bool, plan, scope, ctes):
        probe = self._inner_scope_probe(sub_q, ctes)
        new_where, corr, neq = self._split_correlation(sub_q, probe, scope, ctes)
        if not corr:
            raise AnalysisError("uncorrelated EXISTS not supported")
        if neq:
            return self._plan_exists_with_neq(sub_q, negated, plan, scope, ctes,
                                              new_where, corr, neq)
        inner_cols = tuple(A.Identifier(ip) for _, ip in corr)
        rewritten = A.Query(
            select=tuple(A.SelectItem(ic, None) for ic in inner_cols),
            from_=sub_q.from_, where=new_where,
        )
        sub_plan, sub_scope = self._analyze_query(rewritten, None, ctes)
        inner = sub_plan.child if isinstance(sub_plan, N.Output) else sub_plan
        sources = sub_plan.sources if isinstance(sub_plan, N.Output) else inner.field_names()
        imap = {f.name: f for f in inner.fields}
        right_keys = tuple(InputRef(imap[s].dtype, s) for s in sources)
        left_keys = []
        for op_, _ in corr:
            f = scope.resolve(op_)
            left_keys.append(InputRef(f.dtype, f.name))
        return N.SemiJoin(plan, inner, tuple(left_keys), right_keys, negated)

    def _plan_exists_with_neq(self, sub_q, negated, plan, scope, ctes,
                              new_where, corr, neq):
        """EXISTS with equality correlation plus ONE ``<>`` correlation
        (Q21 shape): per correlation group, gather min/max of the
        inner inequality column; 'another row with a different value
        exists' iff min <> X or max <> X.
        """
        if len(neq) > 1:
            raise AnalysisError("at most one <> correlation supported in EXISTS")
        outer_x, inner_y = neq[0]
        rewritten = A.Query(
            select=(
                A.SelectItem(A.FunctionCall("min", (A.Identifier(inner_y),)), "mn"),
                A.SelectItem(A.FunctionCall("max", (A.Identifier(inner_y),)), "mx"),
            )
            + tuple(
                A.SelectItem(A.Identifier(ip), f"ck{i}")
                for i, (_, ip) in enumerate(corr)
            ),
            from_=sub_q.from_, where=new_where,
            group_by=tuple(A.Identifier(ip) for _, ip in corr),
        )
        sub_plan, _ = self._analyze_query(rewritten, None, ctes)
        inner = sub_plan.child if isinstance(sub_plan, N.Output) else sub_plan
        sources = sub_plan.sources if isinstance(sub_plan, N.Output) else inner.field_names()
        names = sub_plan.names if isinstance(sub_plan, N.Output) else sources
        smap = dict(zip(names, sources))
        imap = {f.name: f for f in inner.fields}
        mn_n, mx_n = self.fresh("exmn"), self.fresh("exmx")
        ren = N.Project(
            inner,
            tuple(
                (mn_n if f.name == smap["mn"] else mx_n if f.name == smap["mx"]
                 else f.name, InputRef(f.dtype, f.name))
                for f in inner.fields
            ),
        )
        right_keys = tuple(
            InputRef(imap[smap[f"ck{i}"]].dtype, smap[f"ck{i}"])
            for i in range(len(corr))
        )
        left_keys = tuple(
            InputRef(scope.resolve(op_).dtype, scope.resolve(op_).name)
            for op_, _ in corr
        )
        joined = N.Join(plan, ren, "left", left_keys, right_keys, True,
                        (mn_n, mx_n))
        xf = scope.resolve(outer_x)
        x = InputRef(xf.dtype, xf.name)
        mn = InputRef(imap[smap["mn"]].dtype, mn_n)
        mx = InputRef(imap[smap["mx"]].dtype, mx_n)
        matched = Call(BOOLEAN, "is_not_null", (mn,))
        if not negated:
            differs = Call(BOOLEAN, "or", (
                Call(BOOLEAN, "ne", (mn, x)), Call(BOOLEAN, "ne", (mx, x))))
            pred = Call(BOOLEAN, "and", (matched, differs))
        else:
            same = Call(BOOLEAN, "and", (
                Call(BOOLEAN, "eq", (mn, x)), Call(BOOLEAN, "eq", (mx, x))))
            pred = Call(BOOLEAN, "or", (Call(BOOLEAN, "is_null", (mn,)), same))
        return N.Filter(joined, pred)

    def _plan_scalar_compare(self, op, other_ast, sub_q: A.Query, negated, flip,
                             plan, scope, outer, ctes, scalar_binds):
        sub_q = self._as_plain_query(sub_q)
        probe = self._inner_scope_probe(sub_q, ctes)
        new_where, corr, neq = self._split_correlation(sub_q, probe, scope, ctes)
        if neq:
            raise AnalysisError("<> correlation in scalar subquery unsupported")
        fn = _CMP_OPS[op]
        if not corr:
            # uncorrelated: ScalarValue binding
            sub_plan, sub_scope = self._analyze_query(sub_q, None, ctes)
            if len(sub_scope.fields) != 1:
                raise AnalysisError("scalar subquery must produce one column")
            sname = self.fresh("scalar")
            sdtype = sub_scope.fields[0].dtype
            scalar_binds.append(N.ScalarValue(sub_plan, sname, sdtype))
            other = self._expr(other_ast, scope, outer, ctes, scalar_binds)
            args = (Unbound(sdtype, sname), other) if flip else (other, Unbound(sdtype, sname))
            e = Call(BOOLEAN, fn, args)
            if negated:
                e = Call(BOOLEAN, "not", (e,))
            return N.Filter(plan, e)
        # correlated: decorrelate via group-by on correlation columns
        if len(sub_q.select) != 1:
            raise AnalysisError("correlated scalar subquery must select one value")
        val_name = "val"
        rewritten = A.Query(
            select=(A.SelectItem(sub_q.select[0].expr, val_name),)
            + tuple(A.SelectItem(A.Identifier(ip), f"ck{i}") for i, (_, ip) in enumerate(corr)),
            from_=sub_q.from_, where=new_where,
            group_by=tuple(A.Identifier(ip) for _, ip in corr),
        )
        sub_plan, sub_scope = self._analyze_query(rewritten, None, ctes)
        inner = sub_plan.child if isinstance(sub_plan, N.Output) else sub_plan
        # inner fields: val + ck0.. — via Output projection mapping
        sources = sub_plan.sources if isinstance(sub_plan, N.Output) else inner.field_names()
        names = sub_plan.names if isinstance(sub_plan, N.Output) else sources
        smap = dict(zip(names, sources))
        imap = {f.name: f for f in inner.fields}
        right_keys = tuple(
            InputRef(imap[smap[f"ck{i}"]].dtype, smap[f"ck{i}"])
            for i in range(len(corr))
        )
        left_keys = tuple(
            InputRef(scope.resolve(op_).dtype, scope.resolve(op_).name)
            for op_, _ in corr
        )
        vfield = imap[smap[val_name]]
        vname = self.fresh("subval")
        # rename the value column to avoid collisions
        ren = N.Project(
            inner,
            tuple(
                (vname if f.name == vfield.name else f.name,
                 InputRef(f.dtype, f.name))
                for f in inner.fields
            ),
        )
        joined = N.Join(
            plan, ren, "inner", left_keys, right_keys, True, (vname,)
        )
        other = self._expr(other_ast, scope, outer, ctes, scalar_binds)
        vref = InputRef(vfield.dtype, vname)
        args = (vref, other) if flip else (other, vref)
        e = Call(BOOLEAN, fn, args)
        if negated:
            e = Call(BOOLEAN, "not", (e,))
        return N.Filter(joined, e)

    # ------------------------------------------------------------------
    # aggregation planning
    # ------------------------------------------------------------------
    def _plan_aggregate(self, q, plan, scope, outer, ctes, scalar_binds):
        # group keys
        keys: list[tuple[str, Expr]] = []
        key_map: dict[A.Node, tuple[str, DataType]] = {}
        for g in q.group_by:
            e = self._expr(g, scope, outer, ctes, scalar_binds)
            if isinstance(g, A.Identifier):
                f = scope.resolve(g.parts)
                name = f.name
            else:
                name = self.fresh("gkey")
            keys.append((name, e))
            key_map[g] = (name, e.dtype)

        # aggregates from select/having/order
        agg_calls: list[A.FunctionCall] = []
        for it in q.select:
            collect_aggs(it.expr, agg_calls)
        if q.having is not None:
            collect_aggs(q.having, agg_calls)
        for ob in q.order_by:
            collect_aggs(ob.expr, agg_calls)
        # dedupe by AST equality
        uniq: list[A.FunctionCall] = []
        for a in agg_calls:
            if a not in uniq:
                uniq.append(a)

        specs: list[AggSpec] = []
        agg_map: dict[A.FunctionCall, Expr] = {}
        distinct_key_exprs: list[tuple[str, Expr]] = []
        for a in uniq:
            specs_e, mapped = self._plan_one_agg(a, scope, outer, ctes, scalar_binds,
                                                 distinct_key_exprs)
            specs.extend(specs_e)
            agg_map[a] = mapped

        if distinct_key_exprs:
            if len(distinct_key_exprs) > 1:
                raise AnalysisError(
                    "multiple distinct DISTINCT-aggregate arguments are "
                    "not supported"
                )
            # pre-aggregate on keys + the distinct column; the DISTINCT
            # count becomes a count of the pre-groups, and plain
            # aggregates decompose through partials (sum of sums, sum of
            # counts, min of mins, ...) — q95 mixes count(distinct)
            # with sums
            dn, de = distinct_key_exprs[0]
            cds = [s for s in specs if s.kind == "count_distinct"]
            plain = [s for s in specs if s.kind != "count_distinct"]
            partial: list[AggSpec] = []
            final: list[AggSpec] = []
            for s in plain:
                if s.kind not in ("sum", "count", "min", "max"):
                    raise AnalysisError(
                        f"{s.kind} cannot combine with DISTINCT aggregates"
                    )
                pn = self.fresh("pdist")
                partial.append(AggSpec(s.kind, s.input, pn, s.dtype))
                outer_kind = "sum" if s.kind in ("sum", "count") else s.kind
                final.append(
                    AggSpec(outer_kind, InputRef(s.dtype, pn), s.name, s.dtype)
                )
            pre_keys = keys + distinct_key_exprs
            plan = N.Aggregate(plan, tuple(pre_keys), tuple(partial))
            keys = [(n, InputRef(e.dtype, n)) for n, e in keys]
            specs = [
                AggSpec("count", InputRef(de.dtype, dn), s.name, s.dtype)
                for s in cds
            ] + final

        # functional dependencies: keys covered by a unique key of the
        # same relation instance become passengers (Q10/Q18 shape)
        grouping, passengers, bij_subst = self._split_passengers(keys, scope)
        key_names = tuple(n for n, _ in grouping)
        unique_sets = [key_names]
        if bij_subst:
            # substitute each hidden-PK group by its bijective named keys
            alt: list[str] = []
            consumed: set[str] = set()
            for hn, named in bij_subst.items():
                consumed |= set(hn)
            for n in key_names:
                if n not in consumed:
                    alt.append(n)
            for hn, named in bij_subst.items():
                alt.extend(named)
            unique_sets.append(tuple(alt))
        agg = N.Aggregate(plan, tuple(grouping), tuple(specs),
                          tuple(passengers), tuple(unique_sets))
        new_scope = Scope(
            [FieldRef(n, e.dtype, self._binding_of(scope, n), self._column_of(scope, n),
                      self._table_of(scope, n))
             for n, e in keys]
            + [FieldRef(s.name, s.dtype, "", s.name) for s in specs]
        )
        return agg, new_scope, agg_map, key_map

    def _split_passengers(self, keys, scope):
        """Partition group keys into (grouping, passengers)."""
        by_binding: dict[str, list[tuple[str, Expr]]] = {}
        fmap = {f.name: f for f in scope.fields}
        for n, e in keys:
            f = fmap.get(n)
            b = f.binding if f is not None and f.table is not None else None
            by_binding.setdefault(b, []).append((n, e))
        grouping: list[tuple[str, Expr]] = []
        passengers: list[tuple[str, Expr]] = []
        bij_subst: dict[tuple[str, ...], tuple[str, ...]] = {}

        def narrow(t: DataType) -> bool:
            return not (t.kind is TypeKind.BYTES and t.width > 7)

        for b, ks in by_binding.items():
            if b is None:
                grouping.extend(ks)
                continue
            f0 = fmap[ks[0][0]]
            uks = self.catalog.unique_keys(f0.table) if f0.table else ()
            cols = {fmap[n].column for n, _ in ks}
            # declared functional dependencies (connector metadata, e.g.
            # tpcds i_brand <- i_brand_id): a determined column whose
            # determinants are all among the keys rides as a passenger
            fdeps = self.catalog.func_deps(f0.table) if f0.table else {}
            if fdeps:
                # closure-grounded demotion: a key may become a
                # passenger only when it is in the functional CLOSURE of
                # the keys that would remain — sound under transitive
                # chains (b<-a, c<-b demotes both b and c) AND under
                # cyclic declared deps (b<-c, c<-b keeps one of them;
                # naive one-shot demotion collapsed the grouping)
                def closure(base: set) -> set:
                    out = set(base)
                    grew = True
                    while grew:
                        grew = False
                        for c, dets in fdeps.items():
                            if c not in out and set(dets) <= out:
                                out.add(c)
                                grew = True
                    return out

                remaining = list(ks)
                det = []
                for k in list(remaining):
                    if len(remaining) == 1:
                        break
                    cand_cols = {
                        fmap[n].column for n, _ in remaining if n != k[0]
                    }
                    if fmap[k[0]].column in closure(cand_cols):
                        remaining = [x for x in remaining if x[0] != k[0]]
                        det.append(k)
                if det:
                    passengers.extend(det)
                    ks = remaining
                    cols = {fmap[n].column for n, _ in ks}
                    if not ks:
                        continue
            chosen = None
            for uk in uks:
                if set(uk) <= cols and all(
                    narrow(fmap[n].dtype) for n, _ in ks if fmap[n].column in set(uk)
                ):
                    chosen = set(uk)
                    break
            if chosen is not None:
                for n, e in ks:
                    if fmap[n].column in chosen:
                        grouping.append((n, e))
                    else:
                        passengers.append((n, e))
                continue
            if all(narrow(e.dtype) for _, e in ks):
                # all keys groupable directly — no dependency tricks
                grouping.extend(ks)
                continue
            # hidden-PK grouping (only when a wide BYTES key forces it):
            # the named keys COVER some unique key of the relation (so
            # row groups == named-key groups, a bijection), but that key
            # is wide — substitute a narrow unique key from the child
            # scope and demote every named key to a passenger.
            covered = any(set(uk) <= cols for uk in uks)
            hidden = None
            if covered:
                for uk in uks:
                    fs = [
                        f for c in uk
                        for f in scope.fields
                        if f.binding == b and f.column == c
                    ]
                    if len(fs) == len(uk) and all(narrow(f.dtype) for f in fs):
                        hidden = fs
                        break
            if hidden is not None:
                for f in hidden:
                    grouping.append((f.name, InputRef(f.dtype, f.name)))
                passengers.extend(ks)
                # bijection: named-key groups == hidden-PK groups, so
                # the named keys covering a unique key of the relation
                # (the smallest covered one — tighter unique sets make
                # more joins provably unique) substitute for the hidden
                # PK in the alternative unique set
                cover = min(
                    (set(uk) for uk in uks if set(uk) <= cols),
                    key=len,
                )
                bij_subst[tuple(f.name for f in hidden)] = tuple(
                    n for n, _ in ks if fmap[n].column in cover
                )
                continue
            grouping.extend(ks)
        # wide BYTES group keys are supported directly (chunked int64
        # surrogates); the unique-key/FD demotions above remain as
        # optimizations, not requirements
        return grouping, passengers, bij_subst

    def _binding_of(self, scope, name):
        for f in scope.fields:
            if f.name == name:
                return f.binding
        return ""

    def _column_of(self, scope, name):
        for f in scope.fields:
            if f.name == name:
                return f.column
        return name

    def _table_of(self, scope, name):
        for f in scope.fields:
            if f.name == name:
                return f.table
        return None

    def _plan_one_agg(self, a: A.FunctionCall, scope, outer, ctes, scalar_binds,
                      distinct_keys_out):
        """One AST aggregate -> ([AggSpec...], post-agg Expr)."""
        nm = self.fresh(a.name)
        if a.name == "count":
            if a.is_star or not a.args:
                spec = AggSpec("count_star", None, nm, BIGINT)
                return [spec], InputRef(BIGINT, nm)
            arg = self._expr(a.args[0], scope, outer, ctes, scalar_binds)
            if a.distinct:
                dk = self.fresh("dkey")
                distinct_keys_out.append((dk, arg))
                spec = AggSpec("count_distinct", InputRef(arg.dtype, dk), nm, BIGINT)
                return [spec], InputRef(BIGINT, nm)
            return [AggSpec("count", arg, nm, BIGINT)], InputRef(BIGINT, nm)
        arg = self._expr(a.args[0], scope, outer, ctes, scalar_binds)
        if a.distinct:
            raise AnalysisError(f"DISTINCT {a.name} not supported")
        if a.name == "avg":
            s = self.fresh("avgsum")
            c = self.fresh("avgcnt")
            sum_t = self._sum_type(arg.dtype)
            specs = [
                AggSpec("sum", arg, s, sum_t),
                AggSpec("count", arg, c, BIGINT),
            ]
            div = Call(DOUBLE, "div", (InputRef(sum_t, s), InputRef(BIGINT, c)))
            return specs, div
        if a.name == "sum":
            t = self._sum_type(arg.dtype)
            return [AggSpec("sum", arg, nm, t)], InputRef(t, nm)
        if a.name in ("min", "max"):
            return [AggSpec(a.name, arg, nm, arg.dtype)], InputRef(arg.dtype, nm)
        if a.name in ("stddev_samp", "stddev", "var_samp", "variance"):
            # decompose to (sum x, sum x^2, count): var = (q - s^2/c)/(c-1)
            # c<=1 yields NULL for free (div-by-zero invalidates)
            d = Call(DOUBLE, "cast_double", (arg,))
            s = self.fresh("vsum")
            qn = self.fresh("vsq")
            c = self.fresh("vcnt")
            specs = [
                AggSpec("sum", d, s, DOUBLE),
                AggSpec("sum", Call(DOUBLE, "mul", (d, d)), qn, DOUBLE),
                AggSpec("count", arg, c, BIGINT),
            ]
            sr, qr, cr = InputRef(DOUBLE, s), InputRef(DOUBLE, qn), InputRef(BIGINT, c)
            mean_sq = Call(DOUBLE, "div", (Call(DOUBLE, "mul", (sr, sr)), cr))
            var = Call(DOUBLE, "div", (
                Call(DOUBLE, "sub", (qr, mean_sq)),
                Call(BIGINT, "sub", (cr, Literal(BIGINT, 1))),
            ))
            if a.name in ("stddev_samp", "stddev"):
                # clamp fp cancellation noise below zero
                clamped = Call(DOUBLE, "if", (
                    Call(BOOLEAN, "lt", (var, Literal(DOUBLE, 0.0))),
                    Literal(DOUBLE, 0.0), var,
                ))
                return specs, Call(DOUBLE, "sqrt", (clamped,))
            return specs, var
        raise AnalysisError(f"unknown aggregate {a.name}")

    def _sum_type(self, t: DataType) -> DataType:
        if t.kind is TypeKind.DECIMAL:
            return decimal(38, t.scale)
        if t.kind is TypeKind.INTEGER:
            return BIGINT
        return t

    # ------------------------------------------------------------------
    # window planning
    # ------------------------------------------------------------------
    def _plan_windows(self, win_calls, plan, scope, outer, ctes, scalar_binds,
                      agg_map, key_map):
        """Plan all window calls: one Window node per distinct OVER
        spec, chained (reference: WindowNode per window; the planner
        merges same-spec functions into one node)."""
        win_map: dict[A.FunctionCall, Expr] = {}
        groups: dict[A.WindowSpec, list[A.FunctionCall]] = {}
        for w in win_calls:
            groups.setdefault(w.over, []).append(w)
        new_fields: list[N.Field] = []
        for spec, calls in groups.items():
            part = tuple(
                self._expr(p, scope, outer, ctes, scalar_binds, agg_map, key_map)
                for p in spec.partition_by
            )
            okeys = tuple(
                SortKey(
                    self._expr(it.expr, scope, outer, ctes, scalar_binds,
                               agg_map, key_map),
                    it.descending, bool(it.nulls_first),
                )
                for it in spec.order_by
            )
            funcs: list[AggSpec] = []
            for w in calls:
                if w in win_map:
                    continue
                specs, mapped = self._plan_one_window_func(
                    w, spec, scope, outer, ctes, scalar_binds, agg_map, key_map
                )
                funcs.extend(specs)
                win_map[w] = mapped
            plan = N.Window(plan, part, okeys, tuple(funcs), spec.frame)
            # window outputs are NOT added to the name scope: they are
            # referenced only through Resolved slots, so SELECT * never
            # leaks the synthetic columns
            new_fields += [N.Field(f.name, f.dtype) for f in funcs]
        return plan, win_map, new_fields

    def _plan_one_window_func(self, w: A.FunctionCall, spec, scope, outer, ctes,
                              scalar_binds, agg_map, key_map):
        nm = self.fresh(w.name)
        if w.distinct:
            raise AnalysisError(f"DISTINCT in window function {w.name}")
        if w.name in WINDOW_ONLY_FUNCS:
            if w.args:
                raise AnalysisError(f"{w.name}() takes no arguments")
            if not spec.order_by:
                raise AnalysisError(f"{w.name}() requires ORDER BY in its window")
            return [AggSpec(w.name, None, nm, BIGINT)], InputRef(BIGINT, nm)
        if w.name in ("lag", "lead", "first_value"):
            if not spec.order_by:
                raise AnalysisError(f"{w.name}() requires ORDER BY in its window")
            offset = 1
            if w.name in ("lag", "lead") and len(w.args) == 2:
                if not isinstance(w.args[1], A.NumberLit):
                    raise AnalysisError(f"{w.name}() offset must be a literal")
                try:
                    offset = int(w.args[1].text)
                except ValueError:
                    raise AnalysisError(
                        f"{w.name}() offset must be an integer literal, "
                        f"got {w.args[1].text!r}"
                    ) from None
            elif len(w.args) != 1:
                raise AnalysisError(f"{w.name}() takes one argument")
            arg = self._expr(w.args[0], scope, outer, ctes, scalar_binds,
                             agg_map, key_map)
            spec_ = AggSpec(w.name, arg, nm, arg.dtype, offset=offset)
            return [spec_], InputRef(arg.dtype, nm)
        if w.name == "count":
            if w.is_star or not w.args:
                return [AggSpec("count_star", None, nm, BIGINT)], InputRef(BIGINT, nm)
            arg = self._expr(w.args[0], scope, outer, ctes, scalar_binds,
                             agg_map, key_map)
            return [AggSpec("count", arg, nm, BIGINT)], InputRef(BIGINT, nm)
        if w.name not in AGG_FUNCS:
            raise AnalysisError(f"unknown window function {w.name}")
        if len(w.args) != 1:
            raise AnalysisError(f"{w.name}() window aggregate takes one argument")
        arg = self._expr(w.args[0], scope, outer, ctes, scalar_binds,
                         agg_map, key_map)
        if w.name == "avg":
            s, c = self.fresh("wavgsum"), self.fresh("wavgcnt")
            sum_t = self._sum_type(arg.dtype)
            specs = [AggSpec("sum", arg, s, sum_t), AggSpec("count", arg, c, BIGINT)]
            return specs, Call(DOUBLE, "div", (InputRef(sum_t, s), InputRef(BIGINT, c)))
        if w.name == "sum":
            t = self._sum_type(arg.dtype)
            return [AggSpec("sum", arg, nm, t)], InputRef(t, nm)
        # min / max: numeric and dictionary VARCHAR (order-preserving
        # codes); raw byte strings have no 1-D scan representation
        if arg.dtype.kind is TypeKind.BYTES:
            raise AnalysisError(
                f"{w.name}() window over byte-string columns is not supported"
            )
        return [AggSpec(w.name, arg, nm, arg.dtype)], InputRef(arg.dtype, nm)

    # ------------------------------------------------------------------
    # order-by resolution
    # ------------------------------------------------------------------
    def _order_expr(self, e, out_scope, pre_scope, outer, ctes, scalar_binds,
                    agg_map, key_map, src_map=None):
        if isinstance(e, A.Identifier) and len(e.parts) == 1:
            f = out_scope.try_resolve(e.parts)
            if f is not None:
                return InputRef(f.dtype, f.name)
            if src_map:
                # ORDER BY a source column that the select ALIASES
                # (ORDER BY c_customer_id with `c_customer_id as id`)
                f = pre_scope.try_resolve(e.parts)
                if f is not None and f.name in src_map:
                    return InputRef(f.dtype, src_map[f.name])
        if isinstance(e, A.Identifier) and len(e.parts) > 1 and src_map:
            # qualified ref (ORDER BY t.col): resolve in the FROM scope,
            # then map back to the output column that projects it — the
            # Sort sits above the projection
            f = pre_scope.try_resolve(e.parts)
            if f is not None and f.name in src_map:
                return InputRef(f.dtype, src_map[f.name])
        if isinstance(e, A.NumberLit):
            idx = int(e.text) - 1
            f = out_scope.fields[idx]
            return InputRef(f.dtype, f.name)
        # fall back: expression over output scope fields by column name
        return self._expr(e, out_scope, outer, ctes, scalar_binds,
                          agg_map=agg_map, key_map=key_map)

    # ------------------------------------------------------------------
    # expression building
    # ------------------------------------------------------------------
    def _expr(self, n: A.Node, scope: Scope, outer, ctes, scalar_binds,
              agg_map=None, key_map=None) -> Expr:
        if isinstance(n, A.Resolved):
            return n.expr
        if key_map and n in key_map:
            name, t = key_map[n]
            return InputRef(t, name)
        if agg_map and isinstance(n, A.FunctionCall) and n in agg_map:
            return agg_map[n]
        if isinstance(n, A.FunctionCall) and n.over is not None:
            raise AnalysisError(
                f"window function {n.name}() is only allowed in SELECT/ORDER BY"
            )
        if isinstance(n, A.Identifier):
            if n.parts == ("null",):
                raise AnalysisError("bare NULL literal needs a typed context")
            f = scope.resolve(n.parts)
            return InputRef(f.dtype, f.name)
        if isinstance(n, A.NumberLit):
            return self._number(n.text)
        if isinstance(n, A.StringLit):
            return Literal(varchar(), n.value)
        if isinstance(n, A.DateLit):
            days = int(
                (np.datetime64(n.value, "D") - np.datetime64("1970-01-01", "D")).astype(int)
            )
            return Literal(DATE, days)
        if isinstance(n, A.TimestampLit):
            from presto_tpu.types import TIMESTAMP

            return Literal(TIMESTAMP, TIMESTAMP.to_physical(n.value))
        if isinstance(n, A.Placeholder):
            raise AnalysisError(
                f"cannot infer the type of parameter ?{n.ordinal + 1}: use "
                "it in a comparison or arithmetic with a typed operand"
            )
        if isinstance(n, A.BinaryOp):
            # placeholder typing: one side a ``?``, the other typed —
            # the parameter takes the typed side's type (the reference's
            # parameter-type-inference rule, narrowed to the contexts
            # this dialect supports)
            l_ph = isinstance(n.left, A.Placeholder)
            r_ph = isinstance(n.right, A.Placeholder)
            if (l_ph or r_ph) and n.op in (_CMP_OPS | _ARITH_OPS):
                if l_ph and r_ph:
                    raise AnalysisError(
                        "cannot infer parameter types: both comparison "
                        "sides are ?")
                typed = self._expr(n.right if l_ph else n.left, scope, outer,
                                   ctes, scalar_binds, agg_map, key_map)
                ph = self._param(n.left if l_ph else n.right, typed.dtype)
                l, r = (ph, typed) if l_ph else (typed, ph)
                if n.op in _CMP_OPS:
                    return Call(BOOLEAN, _CMP_OPS[n.op], (l, r))
                fn = _ARITH_OPS[n.op]
                t = result_type(fn, [l.dtype, r.dtype])
                return Call(t, fn, (l, r))
            if n.op in ("and", "or"):
                l = self._expr(n.left, scope, outer, ctes, scalar_binds, agg_map, key_map)
                r = self._expr(n.right, scope, outer, ctes, scalar_binds, agg_map, key_map)
                return Call(BOOLEAN, n.op, (l, r))
            if n.op in _CMP_OPS:
                l = self._expr(n.left, scope, outer, ctes, scalar_binds, agg_map, key_map)
                r = self._expr(n.right, scope, outer, ctes, scalar_binds, agg_map, key_map)
                return Call(BOOLEAN, _CMP_OPS[n.op], (l, r))
            if n.op == "||":
                l = self._expr(n.left, scope, outer, ctes, scalar_binds, agg_map, key_map)
                r = self._expr(n.right, scope, outer, ctes, scalar_binds, agg_map, key_map)
                width = 0
                args: tuple = ()
                for side in (l, r):
                    if side.dtype.kind is TypeKind.BYTES:
                        width += side.dtype.width
                    elif (isinstance(side, Literal)
                          and side.dtype.kind is TypeKind.VARCHAR):
                        width += len(side.value)
                    else:
                        raise AnalysisError("|| requires string operands")
                    # flatten chained concats into one Call
                    if isinstance(side, Call) and side.fn == "concat":
                        args += side.args
                    else:
                        args += (side,)
                from presto_tpu.types import fixed_bytes

                return Call(fixed_bytes(width), "concat", args)
            if n.op in _ARITH_OPS:
                # date +/- interval folding
                folded = self._fold_date_arith(n, scope, outer, ctes, scalar_binds,
                                               agg_map, key_map)
                if folded is not None:
                    return folded
                l = self._expr(n.left, scope, outer, ctes, scalar_binds, agg_map, key_map)
                r = self._expr(n.right, scope, outer, ctes, scalar_binds, agg_map, key_map)
                fn = _ARITH_OPS[n.op]
                t = result_type(fn, [l.dtype, r.dtype])
                return Call(t, fn, (l, r))
            raise AnalysisError(f"unknown operator {n.op}")
        if isinstance(n, A.UnaryOp):
            if n.op == "not":
                return Call(BOOLEAN, "not",
                            (self._expr(n.operand, scope, outer, ctes, scalar_binds,
                                        agg_map, key_map),))
            v = self._expr(n.operand, scope, outer, ctes, scalar_binds, agg_map, key_map)
            return Call(v.dtype, "neg", (v,))
        if isinstance(n, A.Between):
            v = self._expr(n.value, scope, outer, ctes, scalar_binds, agg_map, key_map)

            def bound(b):
                if isinstance(b, A.Placeholder):
                    return self._param(b, v.dtype)
                return self._expr(b, scope, outer, ctes, scalar_binds,
                                  agg_map, key_map)

            e = Call(BOOLEAN, "between", (v, bound(n.low), bound(n.high)))
            return Call(BOOLEAN, "not", (e,)) if n.negated else e
        if isinstance(n, A.InList):
            v = self._expr(n.value, scope, outer, ctes, scalar_binds, agg_map, key_map)
            items = tuple(
                self._param(i, v.dtype) if isinstance(i, A.Placeholder)
                else self._expr(i, scope, outer, ctes, scalar_binds, agg_map,
                                key_map)
                for i in n.items
            )
            e = Call(BOOLEAN, "in", (v,) + items)
            return Call(BOOLEAN, "not", (e,)) if n.negated else e
        if isinstance(n, A.Like):
            v = self._expr(n.value, scope, outer, ctes, scalar_binds, agg_map, key_map)
            if not isinstance(n.pattern, A.StringLit):
                raise AnalysisError("LIKE pattern must be a literal")
            e = Call(BOOLEAN, "like", (v, Literal(varchar(), n.pattern.value)))
            return Call(BOOLEAN, "not", (e,)) if n.negated else e
        if isinstance(n, A.IsNull):
            v = self._expr(n.value, scope, outer, ctes, scalar_binds, agg_map, key_map)
            return Call(BOOLEAN, "is_not_null" if n.negated else "is_null", (v,))
        if isinstance(n, A.CaseExpr):
            return self._case(n, scope, outer, ctes, scalar_binds, agg_map, key_map)
        if isinstance(n, A.Cast):
            v = self._expr(n.value, scope, outer, ctes, scalar_binds, agg_map, key_map)
            return self._cast(v, n.type_name)
        if isinstance(n, A.Extract):
            v = self._expr(n.value, scope, outer, ctes, scalar_binds, agg_map, key_map)
            field = {"dow": "day_of_week", "doy": "day_of_year",
                     "day_of_week": "day_of_week",
                     "day_of_year": "day_of_year"}.get(n.field, n.field)
            if field not in ("year", "month", "day", "quarter",
                             "day_of_week", "day_of_year",
                             "hour", "minute", "second"):
                raise AnalysisError(f"EXTRACT({n.field}) unsupported")
            return Call(INTEGER, field, (v,))
        if isinstance(n, A.Substring):
            v = self._expr(n.value, scope, outer, ctes, scalar_binds, agg_map, key_map)
            start_node = n.start
            start_neg = False
            if (isinstance(start_node, A.UnaryOp) and start_node.op == "-"):
                start_neg, start_node = True, start_node.operand
            if not (isinstance(start_node, A.NumberLit)
                    and (n.length is None or isinstance(n.length, A.NumberLit))):
                raise AnalysisError("SUBSTRING bounds must be literals")
            start = -int(start_node.text) if start_neg else int(start_node.text)
            if start < 1 and v.dtype.kind is not TypeKind.VARCHAR:
                raise AnalysisError(
                    "negative SUBSTRING start requires a dictionary VARCHAR")
            if v.dtype.kind is TypeKind.VARCHAR:
                # general dictionary substr: derived-dictionary transform
                from presto_tpu.expr import substr_dict_fn

                length = (int(n.length.text) if n.length is not None
                          else 1 << 20)
                return Call(v.dtype, substr_dict_fn(start, length), (v,))
            length = int(n.length.text) if n.length is not None else (
                v.dtype.width - start + 1
            )
            fn = substr_fn(start, length)
            from presto_tpu.types import fixed_bytes

            return Call(fixed_bytes(length), fn, (v,))
        if isinstance(n, A.FunctionCall):
            if n.name in AGG_FUNCS:
                raise AnalysisError(f"aggregate {n.name} in scalar context")
            if n.name in ("year", "month", "day"):
                v = self._expr(n.args[0], scope, outer, ctes, scalar_binds, agg_map, key_map)
                return Call(INTEGER, n.name, (v,))
            if n.name == "abs":
                v = self._expr(n.args[0], scope, outer, ctes, scalar_binds, agg_map, key_map)
                return Call(v.dtype, "abs", (v,))
            if n.name in ("upper", "lower"):
                v = self._expr(n.args[0], scope, outer, ctes, scalar_binds, agg_map, key_map)
                if v.dtype.kind is not TypeKind.BYTES:
                    raise AnalysisError(f"{n.name}() requires a BYTES string")
                return Call(v.dtype, n.name, (v,))
            if n.name in ("sqrt", "floor", "ceil", "ceiling"):
                v = self._expr(n.args[0], scope, outer, ctes, scalar_binds, agg_map, key_map)
                fn = "ceil" if n.name == "ceiling" else n.name
                return Call(DOUBLE, fn, (v,))
            if n.name == "round":
                v = self._expr(n.args[0], scope, outer, ctes, scalar_binds, agg_map, key_map)
                if len(n.args) == 2:
                    if not isinstance(n.args[1], A.NumberLit):
                        raise AnalysisError("round() scale must be a literal")
                    nd = int(n.args[1].text)
                    scale = Literal(DOUBLE, float(10 ** nd))
                    scaled = Call(DOUBLE, "mul", (Call(DOUBLE, "cast_double", (v,)), scale))
                    return Call(DOUBLE, "div", (Call(DOUBLE, "round", (scaled,)), scale))
                return Call(DOUBLE, "round", (v,))
            if n.name == "nullif":
                a = self._expr(n.args[0], scope, outer, ctes, scalar_binds, agg_map, key_map)
                b = self._expr(n.args[1], scope, outer, ctes, scalar_binds, agg_map, key_map)
                eq = Call(BOOLEAN, "eq", (a, b))
                return Call(a.dtype, "if", (eq, Literal(a.dtype, None), a))
            if n.name == "coalesce":
                args = tuple(
                    self._expr(a, scope, outer, ctes, scalar_binds, agg_map, key_map)
                    for a in n.args
                )
                from presto_tpu.types import common_super_type

                t = args[0].dtype
                for a in args[1:]:
                    t = common_super_type(t, a.dtype)
                return Call(t, "coalesce", args)
            handled = self._scalar_function(n, scope, outer, ctes,
                                            scalar_binds, agg_map, key_map)
            if handled is not None:
                return handled
            raise AnalysisError(f"unknown function {n.name}")
        if isinstance(n, A.ScalarSubquery):
            # scalar subquery in a value position (uncorrelated only)
            sub_plan, sub_scope = self._analyze_any(n.query, None, ctes)
            if len(sub_scope.fields) != 1:
                raise AnalysisError("scalar subquery must produce one column")
            sname = self.fresh("scalar")
            t = sub_scope.fields[0].dtype
            scalar_binds.append(N.ScalarValue(sub_plan, sname, t))
            return Unbound(t, sname)
        raise AnalysisError(f"unsupported expression {type(n).__name__}")

    def _scalar_function(self, n: A.FunctionCall, scope, outer, ctes,
                         scalar_binds, agg_map, key_map):
        """Round-5 scalar-function breadth (SURVEY §2.1 functions row):
        math, string, and date families beyond the bootstrap set. Returns
        None for unknown names (caller raises)."""
        from presto_tpu.expr import (
            date_add_fn,
            date_diff_fn,
            date_trunc_fn,
            split_part_fn,
            substr_dict_fn,
        )

        _ARITY = {"quarter": 1, "day_of_week": 1, "dow": 1,
                  "day_of_year": 1, "doy": 1, "last_day_of_month": 1,
                  "hour": 1, "minute": 1, "second": 1,
                  "date_trunc": 2, "date_add": 3, "date_diff": 3,
                  "length": 1, "char_length": 1, "character_length": 1,
                  "trim": 1, "ltrim": 1, "rtrim": 1, "reverse": 1,
                  "strpos": 2, "replace": 3, "split_part": 3,
                  "regexp_like": 2, "power": 2, "pow": 2, "exp": 1,
                  "ln": 1, "log10": 1, "log2": 1, "truncate": 1,
                  "sign": 1, "mod": 2}
        want = _ARITY.get(n.name)
        if want is not None and len(n.args) != want:
            raise AnalysisError(
                f"{n.name}() expects {want} argument(s), got {len(n.args)}")
        if n.name == "substr" and len(n.args) not in (2, 3):
            raise AnalysisError("substr() expects 2 or 3 arguments")
        if n.name in ("greatest", "least") and len(n.args) < 2:
            raise AnalysisError(f"{n.name}() expects at least 2 arguments")

        def sub(i):
            return self._expr(n.args[i], scope, outer, ctes, scalar_binds,
                              agg_map, key_map)

        def str_lit(i, what):
            a = n.args[i]
            if not isinstance(a, A.StringLit):
                raise AnalysisError(f"{n.name}() {what} must be a string literal")
            return a.value

        def int_lit(i, what):
            a = n.args[i]
            neg = False
            if isinstance(a, A.UnaryOp) and a.op == "-":
                neg, a = True, a.operand
            if not isinstance(a, A.NumberLit):
                raise AnalysisError(f"{n.name}() {what} must be an integer literal")
            v = int(a.text)
            return -v if neg else v

        name = n.name
        if name in ("hour", "minute", "second"):
            return Call(INTEGER, name, (sub(0),))
        if name in ("quarter", "day_of_week", "dow", "day_of_year", "doy"):
            canon = {"dow": "day_of_week", "doy": "day_of_year"}.get(name, name)
            return Call(INTEGER, canon, (sub(0),))
        if name == "last_day_of_month":
            return Call(DATE, "last_day_of_month", (sub(0),))
        if name == "date_trunc":
            v = sub(1)
            return Call(v.dtype, date_trunc_fn(str_lit(0, "unit")), (v,))
        if name == "date_add":
            return Call(DATE, date_add_fn(str_lit(0, "unit")),
                        (sub(1), sub(2)))
        if name == "date_diff":
            return Call(BIGINT, date_diff_fn(str_lit(0, "unit")),
                        (sub(1), sub(2)))
        if name in ("length", "char_length", "character_length"):
            return Call(INTEGER, "length", (sub(0),))
        if name in ("trim", "ltrim", "rtrim", "reverse"):
            v = sub(0)
            return Call(v.dtype, name, (v,))
        if name == "strpos":
            v = sub(0)
            return Call(INTEGER, "strpos",
                        (v, Literal(varchar(), str_lit(1, "needle"))))
        if name == "replace":
            v = sub(0)
            return Call(v.dtype, "replace",
                        (v, Literal(varchar(), str_lit(1, "search")),
                         Literal(varchar(), str_lit(2, "replacement"))))
        if name == "split_part":
            v = sub(0)
            fn = split_part_fn(str_lit(1, "separator"), int_lit(2, "index"))
            return Call(v.dtype, fn, (v,))
        if name == "regexp_like":
            v = sub(0)
            return Call(BOOLEAN, "regexp_like",
                        (v, Literal(varchar(), str_lit(1, "pattern"))))
        if name == "substr":
            length = (A.NumberLit(str(int_lit(2, "length")))
                      if len(n.args) >= 3 else None)
            start = n.args[1]
            return self._expr(A.Substring(n.args[0], start, length), scope,
                              outer, ctes, scalar_binds, agg_map, key_map)
        if name in ("greatest", "least"):
            from presto_tpu.types import common_super_type

            args = tuple(sub(i) for i in range(len(n.args)))
            t = args[0].dtype
            for a in args[1:]:
                t = common_super_type(t, a.dtype)
            return Call(t, name, args)
        if name in ("power", "pow"):
            return Call(DOUBLE, "power", (sub(0), sub(1)))
        if name in ("exp", "ln", "log10", "log2", "truncate"):
            return Call(DOUBLE, name, (sub(0),))
        if name == "sign":
            return Call(INTEGER, "sign", (sub(0),))
        if name == "mod":
            from presto_tpu.types import common_super_type

            a, b = sub(0), sub(1)
            return Call(common_super_type(a.dtype, b.dtype), "mod", (a, b))
        return None

    def _case(self, n: A.CaseExpr, scope, outer, ctes, scalar_binds, agg_map, key_map):
        def is_bare_null(x):
            return isinstance(x, A.Identifier) and x.parts == ("null",)

        # analyze each typed branch exactly ONCE (a branch may carry
        # side effects — a scalar subquery appends a bind); bare NULL
        # branches (THEN NULL / ELSE NULL) then take the common type
        values = [v for _, v in n.whens]
        if n.else_ is not None:
            values.append(n.else_)
        analyzed: list[Expr | None] = [
            None if is_bare_null(v)
            else self._expr(v, scope, outer, ctes, scalar_binds, agg_map,
                            key_map)
            for v in values
        ]
        if any(e is None for e in analyzed):
            typed = [e for e in analyzed if e is not None]
            if not typed:
                raise AnalysisError("CASE with only NULL branches")
            from presto_tpu.types import common_super_type

            null_t = typed[0].dtype
            for e in typed[1:]:
                null_t = common_super_type(null_t, e.dtype)
            analyzed = [
                Literal(null_t, None) if e is None else e for e in analyzed
            ]

        whens = []
        for (c, _), v in zip(n.whens, analyzed):
            if n.operand is not None:
                c = A.BinaryOp("=", n.operand, c)
            whens.append((
                self._expr(c, scope, outer, ctes, scalar_binds, agg_map, key_map),
                v,
            ))
        args: list[Expr] = []
        for c, v in whens:
            args.extend([c, v])
        branch_types = [v.dtype for _, v in whens]
        if n.else_ is not None:
            e = analyzed[-1]
            args.append(e)
            branch_types.append(e.dtype)
        from presto_tpu.types import common_super_type
        t = branch_types[0]
        for bt in branch_types[1:]:
            t = common_super_type(t, bt)
        return Call(t, "case", tuple(args))

    def _cast(self, v: Expr, type_name: str) -> Expr:
        from presto_tpu.expr import rescale_decimal

        if type_name == "double":
            return Call(DOUBLE, "cast_double", (v,))
        if type_name in ("bigint", "int", "integer"):
            return Call(BIGINT, "cast_bigint", (v,))
        if type_name.startswith("decimal"):
            import re as _re

            m = _re.match(r"decimal\((\d+),(\d+)\)", type_name)
            if not m:
                raise AnalysisError(f"bad decimal type {type_name}")
            fn = rescale_decimal(int(m.group(2)))
            return Call(decimal(int(m.group(1)), int(m.group(2))), fn, (v,))
        if type_name == "varchar" or type_name.startswith("varchar("):
            import re as _re

            from presto_tpu.expr import cast_varchar_fn
            from presto_tpu.types import fixed_bytes

            m = _re.match(r"varchar\((\d+)\)", type_name)
            if v.dtype.kind is TypeKind.VARCHAR and m is None:
                return v  # identity
            if m is not None:
                w = int(m.group(1))
            elif v.dtype.kind is TypeKind.BYTES:
                w = v.dtype.width
            else:
                w = {TypeKind.INTEGER: 11, TypeKind.BIGINT: 20,
                     TypeKind.DATE: 10, TypeKind.TIMESTAMP: 19}.get(
                         v.dtype.kind)
                if w is None and v.dtype.kind is TypeKind.DECIMAL:
                    w = v.dtype.precision + 2
                if w is None:
                    raise AnalysisError(f"cast {v.dtype} to varchar unsupported")
            return Call(fixed_bytes(w), cast_varchar_fn(w), (v,))
        if type_name == "timestamp":
            from presto_tpu.types import TIMESTAMP

            from presto_tpu.expr import Literal as _Lit

            if isinstance(v, _Lit) and isinstance(v.value, str):
                return _Lit(TIMESTAMP, v.value)
            if v.dtype.kind is TypeKind.TIMESTAMP:
                return v
            if v.dtype.kind is TypeKind.DATE:
                return Call(TIMESTAMP, "cast_timestamp", (v,))
            if v.dtype.kind is TypeKind.VARCHAR:
                from presto_tpu.expr import parse_timestamp_fn

                return Call(TIMESTAMP, parse_timestamp_fn(), (v,))
            raise AnalysisError(f"cast {v.dtype} to timestamp unsupported")
        if type_name == "date":
            from presto_tpu.expr import Literal as _Lit
            from presto_tpu.expr import parse_date_fn

            if isinstance(v, _Lit) and isinstance(v.value, str):
                return _Lit(DATE, v.value)  # host-parsed at to_physical
            if v.dtype.kind is TypeKind.DATE:
                return v
            if v.dtype.kind is TypeKind.VARCHAR:
                return Call(DATE, parse_date_fn(), (v,))
            raise AnalysisError(f"cast {v.dtype} to date unsupported")
        raise AnalysisError(f"unsupported cast to {type_name}")

    def _number(self, text: str) -> Literal:
        if "." in text:
            frac = text.split(".")[1]
            scale = len(frac)
            prec = len(text.replace(".", ""))
            return Literal(decimal(prec, scale), float(text))
        v = int(text)
        return Literal(INTEGER if -(2**31) <= v < 2**31 else BIGINT, v)

    def _fold_date_arith(self, n: A.BinaryOp, scope, outer, ctes, scalar_binds,
                         agg_map, key_map) -> Expr | None:
        """date_literal +/- interval -> folded DATE literal (calendar
        math on the host at plan time)."""
        if n.op not in ("+", "-"):
            return None
        if not isinstance(n.right, A.IntervalLit):
            return None
        base = self._expr(n.left, scope, outer, ctes, scalar_binds, agg_map, key_map)
        if not (isinstance(base, Literal) and base.dtype == DATE):
            raise AnalysisError("interval arithmetic only on date literals")
        amount = int(n.right.value) * (1 if n.op == "+" else -1)
        d = np.datetime64("1970-01-01", "D") + np.int64(base.value)
        if n.right.unit == "day":
            d2 = d + amount
        elif n.right.unit == "month":
            m = d.astype("datetime64[M]") + amount
            rem = (d - d.astype("datetime64[M]").astype("datetime64[D]")).astype(int)
            d2 = m.astype("datetime64[D]") + rem
        else:  # year
            y = d.astype("datetime64[Y]") + amount
            rem = (d - d.astype("datetime64[Y]").astype("datetime64[D]")).astype(int)
            d2 = y.astype("datetime64[D]") + rem
        days = int((d2 - np.datetime64("1970-01-01", "D")).astype(int))
        return Literal(DATE, days)


