"""SQL abstract syntax tree.

Reference parity: ``com.facebook.presto.sql.tree`` (``Query``,
``QuerySpecification``, ``Select``, ``Join``, ``ComparisonExpression``,
...) [SURVEY §2.1; reference tree unavailable, paths reconstructed].
Small immutable dataclasses; the analyzer turns these into the typed
relational IR — the AST itself is untyped.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------


class Node:
    pass


@dataclass(frozen=True)
class Identifier(Node):
    parts: tuple[str, ...]  # ("o", "custkey") or ("custkey",)

    def __str__(self):
        return ".".join(self.parts)


@dataclass(frozen=True)
class NumberLit(Node):
    text: str  # keep text: "1", "0.05" — analyzer picks int/decimal/double

    def __str__(self):
        return self.text


@dataclass(frozen=True)
class StringLit(Node):
    value: str


@dataclass(frozen=True)
class DateLit(Node):
    value: str  # 'YYYY-MM-DD'

@dataclass(frozen=True)
class TimestampLit(Node):
    value: str  # 'YYYY-MM-DD HH:MM:SS[.ffffff]'


@dataclass(frozen=True)
class IntervalLit(Node):
    value: str
    unit: str  # day | month | year


@dataclass(frozen=True)
class BinaryOp(Node):
    op: str  # + - * / % = <> < <= > >= and or
    left: Node
    right: Node


@dataclass(frozen=True)
class UnaryOp(Node):
    op: str  # - not
    operand: Node


@dataclass(frozen=True)
class WindowSpec(Node):
    """OVER (PARTITION BY ... ORDER BY ... [frame]).

    frame: 'range' (SQL default: RANGE UNBOUNDED PRECEDING..CURRENT
    ROW), 'rows' (ROWS UNBOUNDED PRECEDING..CURRENT ROW), or 'full'
    (UNBOUNDED PRECEDING..UNBOUNDED FOLLOWING = whole partition).
    """

    partition_by: tuple[Node, ...] = ()
    order_by: tuple["OrderItem", ...] = ()
    frame: str = "range"


@dataclass(frozen=True)
class FunctionCall(Node):
    name: str
    args: tuple[Node, ...]
    distinct: bool = False
    is_star: bool = False  # count(*)
    over: Optional[WindowSpec] = None  # window function when set


@dataclass(frozen=True)
class Resolved(Node):
    """An AST slot already lowered to a typed engine Expr (used by the
    analyzer to substitute planned window-function results before the
    SELECT projection pass). ``expr`` is a presto_tpu.expr.Expr."""

    expr: object


@dataclass(frozen=True)
class CaseExpr(Node):
    whens: tuple[tuple[Node, Node], ...]
    else_: Optional[Node]
    operand: Optional[Node] = None  # CASE x WHEN v THEN ...


@dataclass(frozen=True)
class Between(Node):
    value: Node
    low: Node
    high: Node
    negated: bool = False


@dataclass(frozen=True)
class InList(Node):
    value: Node
    items: tuple[Node, ...]
    negated: bool = False


@dataclass(frozen=True)
class InSubquery(Node):
    value: Node
    query: "Query"
    negated: bool = False


@dataclass(frozen=True)
class Exists(Node):
    query: "Query"
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery(Node):
    query: "Query"


@dataclass(frozen=True)
class Like(Node):
    value: Node
    pattern: Node
    negated: bool = False


@dataclass(frozen=True)
class IsNull(Node):
    value: Node
    negated: bool = False


@dataclass(frozen=True)
class Cast(Node):
    value: Node
    type_name: str  # "double", "decimal(12,2)", "date", "bigint", "varchar"


@dataclass(frozen=True)
class Extract(Node):
    field: str  # year | month | day
    value: Node


@dataclass(frozen=True)
class Star(Node):
    qualifier: Optional[str] = None


@dataclass(frozen=True)
class Substring(Node):
    value: Node
    start: Node
    length: Optional[Node]


@dataclass(frozen=True)
class Placeholder(Node):
    """A ``?`` parameter in a PREPAREd statement; ``ordinal`` is the
    0-based lexical position. The analyzer types it from its comparison
    /arithmetic context and lowers it to an ``expr.Param`` slot."""

    ordinal: int


# ---------------------------------------------------------------------------
# relations & query structure
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Table(Node):
    name: str
    alias: Optional[str] = None


@dataclass(frozen=True)
class SubqueryRelation(Node):
    query: "Query"
    alias: Optional[str] = None


@dataclass(frozen=True)
class Join(Node):
    kind: str  # inner | left | right | full | cross
    left: Node
    right: Node
    on: Optional[Node] = None


@dataclass(frozen=True)
class SelectItem(Node):
    expr: Node
    alias: Optional[str] = None


@dataclass(frozen=True)
class OrderItem(Node):
    expr: Node
    descending: bool = False
    nulls_first: Optional[bool] = None


@dataclass(frozen=True)
class GroupingSets(Node):
    """A ROLLUP / CUBE / GROUPING SETS element inside GROUP BY; the
    parser normalizes all three spellings to the explicit set list."""

    sets: tuple[tuple[Node, ...], ...]


@dataclass(frozen=True)
class Query(Node):
    select: tuple[SelectItem, ...]
    from_: Optional[Node]  # relation tree (None for SELECT <expr>)
    where: Optional[Node] = None
    group_by: tuple[Node, ...] = ()  # exprs and/or GroupingSets elements
    having: Optional[Node] = None
    order_by: tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    distinct: bool = False
    ctes: tuple[tuple[str, "Query"], ...] = ()  # WITH name AS (query)


@dataclass(frozen=True)
class CreateTableAs(Node):
    """CREATE TABLE <name> AS <query> (CTAS into the memory catalog)."""

    name: str
    query: Node  # Query | SetQuery


@dataclass(frozen=True)
class InsertInto(Node):
    """INSERT INTO <name> <query> (append, atomic per statement)."""

    name: str
    query: Node


@dataclass(frozen=True)
class DropTable(Node):
    name: str
    if_exists: bool = False


@dataclass(frozen=True)
class Prepare(Node):
    """PREPARE <name> FROM <statement> — store a plan template under a
    session-scoped handle (reference: PREPARE; SURVEY §2.1 protocol)."""

    name: str
    statement: Node  # Query | SetQuery


@dataclass(frozen=True)
class ExecuteStmt(Node):
    """EXECUTE <name> [USING v1, v2, ...] — run a prepared template
    with positional parameter bindings (literals only)."""

    name: str
    args: tuple[Node, ...] = ()


@dataclass(frozen=True)
class Deallocate(Node):
    """DEALLOCATE PREPARE <name> — drop a prepared handle."""

    name: str


@dataclass(frozen=True)
class SetQuery(Node):
    """UNION [ALL] chain. ``ops[i]`` combines ``terms[i]`` into the
    running result ('union' dedups, 'union_all' keeps duplicates);
    ORDER BY / LIMIT apply to the combined result and may reference the
    first term's output names or ordinals."""

    terms: tuple[Node, ...]  # Query | SetQuery
    ops: tuple[str, ...]  # len(terms) - 1
    order_by: tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    ctes: tuple[tuple[str, "Query"], ...] = ()
