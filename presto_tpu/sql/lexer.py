"""SQL tokenizer.

Reference parity: the lexer half of ``presto-parser``'s ANTLR4
``SqlBase.g4`` [SURVEY §2.1; reference tree unavailable]. Hand-rolled
(no ANTLR in a zero-dependency build): one pass, line/col tracked for
error messages.
"""

from __future__ import annotations

from dataclasses import dataclass

from presto_tpu.runtime.errors import UserError


@dataclass(frozen=True)
class Token:
    kind: str  # KW | IDENT | NUMBER | STRING | OP | EOF
    text: str
    pos: int
    line: int
    col: int


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "exists", "between", "like", "is",
    "null", "case", "when", "then", "else", "end", "cast", "extract",
    "date", "timestamp", "interval", "year", "month", "day", "distinct", "join",
    "inner", "left", "right", "full", "outer", "cross", "on", "with",
    "asc", "desc", "nulls", "first", "last", "substring", "union", "all",
    "true", "false", "count", "sum", "avg", "min", "max", "any", "some",
    "for", "over", "partition", "rows", "range", "preceding", "following",
    "current", "row", "unbounded",
}

_TWO_CHAR_OPS = {"<=", ">=", "<>", "!=", "||"}
_ONE_CHAR_OPS = set("+-*/%(),.;=<>?")


class LexError(UserError):
    """Tokenizer rejection. A ``UserError`` (which is also a
    ``ValueError``): malformed SQL must surface through the TYPED
    error contract like every parse/analysis rejection, not as a bare
    built-in exception."""


def tokenize(sql: str) -> list[Token]:
    out: list[Token] = []
    i, n = 0, len(sql)
    line, col = 1, 1

    def advance(k: int):
        nonlocal i, line, col
        for _ in range(k):
            if i < n and sql[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            advance(1)
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":
            while i < n and sql[i] != "\n":
                advance(1)
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":
            advance(2)
            while i + 1 < n and not (sql[i] == "*" and sql[i + 1] == "/"):
                advance(1)
            advance(2)
            continue
        start, sline, scol = i, line, col
        if c.isalpha() or c == "_":
            while i < n and (sql[i].isalnum() or sql[i] == "_"):
                advance(1)
            text = sql[start:i]
            kind = "KW" if text.lower() in KEYWORDS else "IDENT"
            out.append(Token(kind, text, start, sline, scol))
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            seen_dot = False
            while i < n and (sql[i].isdigit() or (sql[i] == "." and not seen_dot)):
                if sql[i] == ".":
                    # "1." followed by non-digit: stop before the dot
                    if i + 1 >= n or not sql[i + 1].isdigit():
                        break
                    seen_dot = True
                advance(1)
            out.append(Token("NUMBER", sql[start:i], start, sline, scol))
            continue
        if c == "'":
            advance(1)
            buf = []
            while True:
                if i >= n:
                    raise LexError(f"unterminated string at line {sline}")
                if sql[i] == "'":
                    if i + 1 < n and sql[i + 1] == "'":
                        buf.append("'")
                        advance(2)
                        continue
                    advance(1)
                    break
                buf.append(sql[i])
                advance(1)
            out.append(Token("STRING", "".join(buf), start, sline, scol))
            continue
        if c == '"':
            advance(1)
            qstart = i
            while i < n and sql[i] != '"':
                advance(1)
            if i >= n:
                raise LexError(f"unterminated quoted identifier at line {sline}")
            # QIDENT: case-preserved (unquoted identifiers fold to lower)
            out.append(Token("QIDENT", sql[qstart:i], qstart, sline, scol))
            advance(1)
            continue
        two = sql[i : i + 2]
        if two in _TWO_CHAR_OPS:
            out.append(Token("OP", "<>" if two == "!=" else two, start, sline, scol))
            advance(2)
            continue
        if c in _ONE_CHAR_OPS:
            out.append(Token("OP", c, start, sline, scol))
            advance(1)
            continue
        raise LexError(f"unexpected character {c!r} at line {line}:{col}")
    out.append(Token("EOF", "", n, line, col))
    return out
