"""Recursive-descent SQL parser for the TPC-H/TPC-DS/SSB dialect subset.

Reference parity: ``presto-parser`` (``SqlParser.createStatement`` over
the ANTLR4 ``SqlBase.g4`` grammar) [SURVEY §2.1; reference tree
unavailable, paths reconstructed]. Hand-rolled per SURVEY §7.2 step 5
(no network, no ANTLR): one token of lookahead, standard precedence
climbing for expressions.
"""

from __future__ import annotations

import dataclasses

from presto_tpu.sql import ast as A
from presto_tpu.runtime.errors import UserError
from presto_tpu.sql.lexer import Token, tokenize


#: contextual (non-reserved) set-operation words: never implicit aliases
_SET_OP_WORDS = ("intersect", "except")


class ParseError(UserError):
    """Syntax errors (taxonomy: USER_ERROR via UserError, which keeps
    the pre-taxonomy ValueError ancestry)."""

    def __init__(self, msg: str, tok: Token):
        super().__init__(f"{msg} at line {tok.line}:{tok.col} (near {tok.text!r})")


class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0
        #: ``?`` placeholders seen so far (ordinals in lex order)
        self.n_params = 0

    # -- token helpers ----------------------------------------------------
    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def kw(self, *words: str) -> bool:
        t = self.cur
        return t.kind == "KW" and t.text.lower() in words

    def op(self, *ops: str) -> bool:
        t = self.cur
        return t.kind == "OP" and t.text in ops

    def word(self, *words: str) -> bool:
        """Match a non-reserved word (lexed as IDENT) or keyword:
        ROLLUP/CUBE/GROUPING/SETS/INTERSECT/EXCEPT are contextual."""
        t = self.cur
        return t.kind in ("KW", "IDENT") and t.text.lower() in words

    def _accept_word(self, w: str) -> bool:
        if self.word(w):
            self.eat()
            return True
        return False

    def _query_follows(self, idx: int) -> bool:
        """True when the tokens at ``idx`` open a query expression,
        possibly through nested parens: ``((select ...`` — the standard
        TPC-DS spelling of parenthesized union terms."""
        j = idx
        while self.toks[j].kind == "OP" and self.toks[j].text == "(":
            j += 1
        t = self.toks[j]
        return t.kind == "KW" and t.text.lower() in ("select", "with")

    def eat(self):
        t = self.cur
        self.i += 1
        return t

    def expect_kw(self, word: str) -> Token:
        if not self.kw(word):
            raise ParseError(f"expected {word.upper()}", self.cur)
        return self.eat()

    def expect_op(self, op: str) -> Token:
        if not self.op(op):
            raise ParseError(f"expected {op!r}", self.cur)
        return self.eat()

    def accept_kw(self, *words: str) -> bool:
        if self.kw(*words):
            self.eat()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.op(*ops):
            self.eat()
            return True
        return False

    # -- entry ------------------------------------------------------------
    def parse(self) -> A.Node:
        q = self.parse_statement()
        self.accept_op(";")
        if self.cur.kind != "EOF":
            raise ParseError("trailing input", self.cur)
        return q

    def parse_statement(self) -> A.Node:
        """Query, CREATE TABLE AS, INSERT INTO, DROP TABLE, or the
        prepared-statement surface (PREPARE / EXECUTE ... USING /
        DEALLOCATE PREPARE)."""
        if self.word("prepare"):
            self.eat()
            name = self.parse_name()
            self.expect_kw("from")
            return A.Prepare(name, self.parse_statement())
        if self.word("execute"):
            self.eat()
            name = self.parse_name()
            args: list[A.Node] = []
            if self._accept_word("using"):
                args.append(self.parse_expr())
                while self.accept_op(","):
                    args.append(self.parse_expr())
            return A.ExecuteStmt(name, tuple(args))
        if self.word("deallocate"):
            self.eat()
            if not self._accept_word("prepare"):
                raise ParseError("expected PREPARE", self.cur)
            return A.Deallocate(self.parse_name())
        if self.word("create"):
            self.eat()
            if not self._accept_word("table"):
                raise ParseError("expected TABLE", self.cur)
            name = self.parse_name()
            self.expect_kw("as")
            return A.CreateTableAs(name, self.parse_query())
        if self.word("insert"):
            self.eat()
            if not self._accept_word("into"):
                raise ParseError("expected INTO", self.cur)
            name = self.parse_name()
            return A.InsertInto(name, self.parse_query())
        if self.word("drop"):
            self.eat()
            if not self._accept_word("table"):
                raise ParseError("expected TABLE", self.cur)
            if_exists = False
            if self.word("if"):
                self.eat()
                if not self._accept_word("exists"):
                    raise ParseError("expected EXISTS", self.cur)
                if_exists = True
            return A.DropTable(self.parse_name(), if_exists)
        return self.parse_query()

    # -- query ------------------------------------------------------------
    def parse_query(self) -> A.Node:
        """[WITH ...] term (UNION [ALL] term)* [ORDER BY ...] [LIMIT n]
        -> Query (no set ops) or SetQuery."""
        ctes: list[tuple[str, A.Query]] = []
        if self.accept_kw("with"):
            while True:
                name = self.parse_name()
                self.expect_kw("as")
                self.expect_op("(")
                ctes.append((name, self.parse_query()))
                self.expect_op(")")
                if not self.accept_op(","):
                    break
        first, first_parenthesized = self._parse_intersect_chain()
        terms = [first]
        ops: list[str] = []
        while self.kw("union") or self.word("except"):
            # UNION and EXCEPT share a precedence level (standard SQL);
            # INTERSECT binds tighter and is folded by the chain below
            if self.word("except"):
                self.eat()
                if self.kw("all"):
                    raise ParseError("EXCEPT ALL not supported", self.cur)
                self.accept_kw("distinct")
                ops.append("except")
            else:
                self.eat()
                if self.accept_kw("all"):
                    ops.append("union_all")
                else:
                    self.accept_kw("distinct")
                    ops.append("union")
            terms.append(self._parse_intersect_chain()[0])
        order_by: list[A.OrderItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by.append(self.parse_order_item())
            while self.accept_op(","):
                order_by.append(self.parse_order_item())
        limit = None
        if self.accept_kw("limit"):
            t = self.eat()
            if t.kind != "NUMBER":
                raise ParseError("expected LIMIT count", t)
            limit = int(t.text)
        if len(terms) == 1:
            q = terms[0]
            if not first_parenthesized:
                # a bare core: its order/limit/ctes slots are empty
                return dataclasses.replace(
                    q, order_by=tuple(order_by), limit=limit, ctes=tuple(ctes)
                )
            # a parenthesized query keeps its own ORDER BY/LIMIT/CTEs;
            # outer clauses (if any) wrap it as a single-term SetQuery
            if not order_by and limit is None and not ctes:
                return q
            return A.SetQuery(
                terms=(q,), ops=(), order_by=tuple(order_by),
                limit=limit, ctes=tuple(ctes),
            )
        return A.SetQuery(
            terms=tuple(terms),
            ops=tuple(ops),
            order_by=tuple(order_by),
            limit=limit,
            ctes=tuple(ctes),
        )

    def _parse_intersect_chain(self) -> tuple[A.Node, bool]:
        """INTERSECT binds tighter than UNION/EXCEPT (standard SQL).
        Set (distinct) semantics only; the ALL variant is rejected."""
        first, parenthesized = self._parse_set_term()
        terms = [first]
        ops: list[str] = []
        while self.word("intersect"):
            self.eat()
            if self.kw("all"):
                raise ParseError("INTERSECT ALL not supported", self.cur)
            self.accept_kw("distinct")
            ops.append("intersect")
            terms.append(self._parse_set_term()[0])
        if len(terms) == 1:
            return first, parenthesized
        return A.SetQuery(terms=tuple(terms), ops=tuple(ops)), True

    def _parse_set_term(self) -> tuple[A.Node, bool]:
        """One UNION operand: a parenthesized query or a bare select
        core (whose ORDER BY/LIMIT, if unparenthesized, belong to the
        enclosing query — standard SQL). Returns (term, parenthesized)."""
        if self.op("(") and self._query_follows(self.i + 1):
            self.eat()
            q = self.parse_query()
            self.expect_op(")")
            return q, True
        return self._parse_select_core(), False

    def _parse_select_core(self) -> A.Query:
        self.expect_kw("select")
        distinct = self.accept_kw("distinct")
        self.accept_kw("all")
        items = [self.parse_select_item()]
        while self.accept_op(","):
            items.append(self.parse_select_item())
        from_ = None
        if self.accept_kw("from"):
            from_ = self.parse_relation_list()
        where = self.parse_expr() if self.accept_kw("where") else None
        group_by: list[A.Node] = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            group_by.append(self._parse_grouping_element())
            while self.accept_op(","):
                group_by.append(self._parse_grouping_element())
        having = self.parse_expr() if self.accept_kw("having") else None
        return A.Query(
            select=tuple(items),
            from_=from_,
            where=where,
            group_by=tuple(group_by),
            having=having,
            distinct=distinct,
        )

    def _parse_grouping_element(self) -> A.Node:
        """GROUP BY element: expr | ROLLUP(...) | CUBE(...) |
        GROUPING SETS ((...), ...) — the latter three normalize to an
        explicit GroupingSets set list."""
        if self.word("rollup") and self.toks[self.i + 1].text == "(":
            self.eat()
            self.expect_op("(")
            exprs = [self.parse_expr()]
            while self.accept_op(","):
                exprs.append(self.parse_expr())
            self.expect_op(")")
            sets = tuple(tuple(exprs[:k]) for k in range(len(exprs), -1, -1))
            return A.GroupingSets(sets)
        if self.word("cube") and self.toks[self.i + 1].text == "(":
            self.eat()
            self.expect_op("(")
            exprs = [self.parse_expr()]
            while self.accept_op(","):
                exprs.append(self.parse_expr())
            self.expect_op(")")
            sets = []
            for mask in range((1 << len(exprs)) - 1, -1, -1):
                sets.append(tuple(
                    e for i, e in enumerate(exprs) if mask & (1 << (len(exprs) - 1 - i))
                ))
            return A.GroupingSets(tuple(sets))
        if self.word("grouping"):
            save = self.i
            self.eat()
            if self._accept_word("sets"):
                self.expect_op("(")
                sets = []
                while True:
                    self.expect_op("(")
                    exprs = []
                    if not self.op(")"):
                        exprs.append(self.parse_expr())
                        while self.accept_op(","):
                            exprs.append(self.parse_expr())
                    self.expect_op(")")
                    sets.append(tuple(exprs))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                return A.GroupingSets(tuple(sets))
            self.i = save  # grouping(...) the function, in an expression
        return self.parse_expr()

    def parse_name(self) -> str:
        t = self.cur
        if t.kind == "QIDENT":
            self.eat()
            return t.text
        if t.kind in ("IDENT", "KW"):
            self.eat()
            return t.text.lower()
        raise ParseError("expected identifier", t)

    def parse_select_item(self) -> A.SelectItem:
        if self.op("*"):
            self.eat()
            return A.SelectItem(A.Star(), None)
        # qualified star: ident.*
        if self.cur.kind == "IDENT" and self.toks[self.i + 1].text == "." and self.toks[
            self.i + 2
        ].text == "*":
            q = self.eat().text.lower()
            self.eat()
            self.eat()
            return A.SelectItem(A.Star(q), None)
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.parse_name()
        elif self.cur.kind == "IDENT" and not self.word(*_SET_OP_WORDS):
            alias = self.eat().text.lower()
        elif self.cur.kind == "QIDENT":
            alias = self.eat().text
        return A.SelectItem(e, alias)

    def parse_order_item(self) -> A.OrderItem:
        e = self.parse_expr()
        desc = False
        if self.accept_kw("desc"):
            desc = True
        elif self.accept_kw("asc"):
            pass
        nulls_first = None
        if self.accept_kw("nulls"):
            if self.accept_kw("first"):
                nulls_first = True
            else:
                self.expect_kw("last")
                nulls_first = False
        return A.OrderItem(e, desc, nulls_first)

    # -- relations --------------------------------------------------------
    def parse_relation_list(self) -> A.Node:
        rel = self.parse_joined_relation()
        while self.accept_op(","):
            rel = A.Join("cross", rel, self.parse_joined_relation())
        return rel

    def parse_joined_relation(self) -> A.Node:
        rel = self.parse_primary_relation()
        while True:
            kind = None
            if self.kw("join", "inner"):
                self.accept_kw("inner")
                self.expect_kw("join")
                kind = "inner"
            elif self.kw("left", "right", "full"):
                kind = self.eat().text.lower()
                self.accept_kw("outer")
                self.expect_kw("join")
            elif self.kw("cross"):
                self.eat()
                self.expect_kw("join")
                rel = A.Join("cross", rel, self.parse_primary_relation())
                continue
            else:
                break
            right = self.parse_primary_relation()
            self.expect_kw("on")
            on = self.parse_expr()
            rel = A.Join(kind, rel, right, on)
        return rel

    def parse_primary_relation(self) -> A.Node:
        if self.op("(") and self._query_follows(self.i + 1):
            # Ambiguous open: a derived table — possibly a parenthesized
            # UNION chain, FROM ((select ...) union all (select ...)) t —
            # or a parenthesized JOIN whose first relation is a subquery,
            # FROM ((select ...) x join y on ...). Try the derived-table
            # parse; backtrack to the join parse on failure (the parser
            # state is just the token index).
            save = self.i
            try:
                self.eat()
                q = self.parse_query()
                self.expect_op(")")
            except ParseError:
                self.i = save
            else:
                alias = self._maybe_alias()
                return A.SubqueryRelation(q, alias)
        if self.accept_op("("):
            rel = self.parse_relation_list()
            self.expect_op(")")
            return rel
        name = self.parse_name()
        alias = self._maybe_alias()
        return A.Table(name, alias)

    def _maybe_alias(self) -> str | None:
        if self.accept_kw("as"):
            return self.parse_name()
        if self.cur.kind == "IDENT" and not self.word(*_SET_OP_WORDS):
            return self.eat().text.lower()
        if self.cur.kind == "QIDENT":
            return self.eat().text
        return None

    # -- expressions ------------------------------------------------------
    def parse_expr(self) -> A.Node:
        return self.parse_or()

    def parse_or(self) -> A.Node:
        e = self.parse_and()
        while self.accept_kw("or"):
            e = A.BinaryOp("or", e, self.parse_and())
        return e

    def parse_and(self) -> A.Node:
        e = self.parse_not()
        while self.accept_kw("and"):
            e = A.BinaryOp("and", e, self.parse_not())
        return e

    def parse_not(self) -> A.Node:
        if self.accept_kw("not"):
            return A.UnaryOp("not", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> A.Node:
        e = self.parse_additive()
        while True:
            if self.op("=", "<>", "<", "<=", ">", ">="):
                op = self.eat().text
                rhs = self.parse_additive_or_quantified()
                e = A.BinaryOp(op, e, rhs)
                continue
            negated = False
            save = self.i
            if self.accept_kw("not"):
                negated = True
            if self.accept_kw("between"):
                low = self.parse_additive()
                self.expect_kw("and")
                high = self.parse_additive()
                e = A.Between(e, low, high, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.kw("select", "with"):
                    q = self.parse_query()
                    self.expect_op(")")
                    e = A.InSubquery(e, q, negated)
                else:
                    items = [self.parse_expr()]
                    while self.accept_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    e = A.InList(e, tuple(items), negated)
                continue
            if self.accept_kw("like"):
                e = A.Like(e, self.parse_additive(), negated)
                continue
            if negated:
                self.i = save  # bare NOT belongs to parse_not
                break
            if self.accept_kw("is"):
                neg = self.accept_kw("not")
                self.expect_kw("null")
                e = A.IsNull(e, neg)
                continue
            break
        return e

    def parse_additive_or_quantified(self) -> A.Node:
        """rhs of a comparison: expr, (subquery), or ANY/ALL(subquery)."""
        if self.kw("any", "some", "all"):
            raise ParseError("quantified comparisons not supported yet", self.cur)
        return self.parse_additive()

    def parse_additive(self) -> A.Node:
        e = self.parse_multiplicative()
        while self.op("+", "-") or (self.cur.kind == "OP" and self.cur.text == "||"):
            op = self.eat().text
            e = A.BinaryOp(op, e, self.parse_multiplicative())
        return e

    def parse_multiplicative(self) -> A.Node:
        e = self.parse_unary()
        while self.op("*", "/", "%"):
            op = self.eat().text
            e = A.BinaryOp(op, e, self.parse_unary())
        return e

    def parse_unary(self) -> A.Node:
        if self.accept_op("-"):
            return A.UnaryOp("-", self.parse_unary())
        if self.accept_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> A.Node:
        t = self.cur
        if self.op("?"):
            self.eat()
            ph = A.Placeholder(self.n_params)
            self.n_params += 1
            return ph
        if t.kind == "NUMBER":
            self.eat()
            return A.NumberLit(t.text)
        if t.kind == "STRING":
            self.eat()
            return A.StringLit(t.text)
        if self.kw("true"):
            self.eat()
            return A.NumberLit("1")  # folded by analyzer as boolean true
        if self.kw("false"):
            self.eat()
            return A.NumberLit("0")
        if self.kw("null"):
            self.eat()
            return A.Identifier(("null",))  # analyzer resolves to NULL literal
        if self.kw("date"):
            self.eat()
            s = self.eat()
            if s.kind != "STRING":
                raise ParseError("expected date string", s)
            return A.DateLit(s.text)
        if self.kw("timestamp"):
            self.eat()
            s = self.eat()
            if s.kind != "STRING":
                raise ParseError("expected timestamp string", s)
            return A.TimestampLit(s.text)
        if self.kw("interval"):
            self.eat()
            s = self.eat()
            if s.kind != "STRING":
                raise ParseError("expected interval string", s)
            unit_tok = self.eat()
            unit = unit_tok.text.lower()
            if unit not in ("day", "month", "year"):
                raise ParseError("expected interval unit", unit_tok)
            return A.IntervalLit(s.text, unit)
        if self.kw("case"):
            return self.parse_case()
        if self.kw("cast"):
            self.eat()
            self.expect_op("(")
            v = self.parse_expr()
            self.expect_kw("as")
            type_name = self.parse_type_name()
            self.expect_op(")")
            return A.Cast(v, type_name)
        if self.kw("extract"):
            self.eat()
            self.expect_op("(")
            field = self.parse_name()
            self.expect_kw("from")
            v = self.parse_expr()
            self.expect_op(")")
            return A.Extract(field, v)
        if self.kw("substring"):
            self.eat()
            self.expect_op("(")
            v = self.parse_expr()
            if self.accept_kw("from"):
                start = self.parse_expr()
                length = self.parse_expr() if self.accept_kw("for") else None
            else:
                self.expect_op(",")
                start = self.parse_expr()
                length = self.parse_expr() if self.accept_op(",") else None
            self.expect_op(")")
            return A.Substring(v, start, length)
        if t.kind == "IDENT" and t.text.lower() == "position":
            # POSITION(needle IN haystack) special form -> strpos
            nxt = self.toks[self.i + 1] if self.i + 1 < len(self.toks) else None
            if nxt is not None and nxt.kind == "OP" and nxt.text == "(":
                self.eat()
                self.eat()
                # additive level: the IN belongs to the POSITION form
                needle = self.parse_additive()
                self.expect_kw("in")
                hay = self.parse_expr()
                self.expect_op(")")
                return A.FunctionCall("strpos", (hay, needle))
        if self.kw("exists"):
            self.eat()
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return A.Exists(q)
        if self.kw("not"):
            self.eat()
            return A.UnaryOp("not", self.parse_primary())
        if self.op("("):
            self.eat()
            if self.kw("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                return A.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        # function call or identifier (agg keywords double as functions)
        if t.kind in ("IDENT", "QIDENT") or self.kw(
            "count", "sum", "avg", "min", "max", "year", "month", "day"
        ):
            name = self.eat().text
            if t.kind != "QIDENT":
                name = name.lower()
            if self.op("("):
                self.eat()
                distinct = self.accept_kw("distinct")
                if self.op("*"):
                    self.eat()
                    self.expect_op(")")
                    return self._maybe_over(A.FunctionCall(name, (), is_star=True))
                args: list[A.Node] = []
                if not self.op(")"):
                    args.append(self.parse_expr())
                    while self.accept_op(","):
                        args.append(self.parse_expr())
                self.expect_op(")")
                return self._maybe_over(
                    A.FunctionCall(name, tuple(args), distinct=distinct)
                )
            parts = [name]
            while self.op(".") and self.toks[self.i + 1].kind in (
                "IDENT", "KW", "QIDENT"
            ):
                self.eat()
                nt = self.eat()
                parts.append(nt.text if nt.kind == "QIDENT" else nt.text.lower())
            return A.Identifier(tuple(parts))
        raise ParseError("unexpected token", t)

    def _maybe_over(self, fc: A.FunctionCall) -> A.FunctionCall:
        if not self.kw("over"):
            return fc
        self.eat()
        self.expect_op("(")
        partition: list[A.Node] = []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition.append(self.parse_expr())
            while self.accept_op(","):
                partition.append(self.parse_expr())
        order: list[A.OrderItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order.append(self.parse_order_item())
            while self.accept_op(","):
                order.append(self.parse_order_item())
        frame = "range"
        if self.kw("rows", "range"):
            unit = self.eat().text.lower()
            frame = self._parse_frame(unit)
        self.expect_op(")")
        spec = A.WindowSpec(tuple(partition), tuple(order), frame)
        return dataclasses.replace(fc, over=spec)

    def _parse_frame(self, unit: str) -> str:
        """Supported frames: [ROWS|RANGE] BETWEEN UNBOUNDED PRECEDING
        AND {CURRENT ROW | UNBOUNDED FOLLOWING}, or the shorthand
        [ROWS|RANGE] UNBOUNDED PRECEDING."""
        if self.accept_kw("between"):
            self.expect_kw("unbounded")
            self.expect_kw("preceding")
            self.expect_kw("and")
            if self.accept_kw("current"):
                self.expect_kw("row")
                return unit  # rows | range
            self.expect_kw("unbounded")
            self.expect_kw("following")
            return "full"
        self.expect_kw("unbounded")
        self.expect_kw("preceding")
        return unit

    def parse_case(self) -> A.CaseExpr:
        self.expect_kw("case")
        operand = None
        if not self.kw("when"):
            operand = self.parse_expr()
        whens = []
        while self.accept_kw("when"):
            c = self.parse_expr()
            self.expect_kw("then")
            v = self.parse_expr()
            whens.append((c, v))
        else_ = self.parse_expr() if self.accept_kw("else") else None
        self.expect_kw("end")
        return A.CaseExpr(tuple(whens), else_, operand)

    def parse_type_name(self) -> str:
        name = self.parse_name()
        if self.accept_op("("):
            params = [self.eat().text]
            while self.accept_op(","):
                params.append(self.eat().text)
            self.expect_op(")")
            return f"{name}({','.join(params)})"
        return name


def parse(sql: str) -> A.Query:
    return Parser(sql).parse()
