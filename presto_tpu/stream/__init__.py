"""Streaming ingestion + continuous queries.

The serving layer's fresh-data tier (ROADMAP item 5): micro-batch
appends land on the memory connector through :class:`StreamWriter`
with INCREMENTAL stats maintenance and per-append version epochs
(connectors/memory.py), and :class:`ContinuousQuery` subscriptions
registered through the server re-execute a prepared plan template
whenever a referenced table's epoch advances (or on an interval
tick). Continuous queries are exactly same-template re-executions, so
they ride the existing template + batched-dispatch path: N dashboards
on one template stack into ONE vmapped dispatch at the
``TemplateBatchGate``, under the ``FairScheduler``'s tenant quotas.

Freshness contract: a delivered result always reflects AT LEAST the
epoch snapshot taken when its refresh fired — structurally guaranteed
because plan fingerprints fold live table versions (a fire at epoch N
can neither coalesce onto nor cache-hit an epoch<N execution), and
asserted at delivery time (``subscription.stale_blocked`` stays 0).
"""

from presto_tpu.stream.subscriptions import (
    ContinuousQuery,
    SubscriptionManager,
    SubscriptionResult,
)
from presto_tpu.stream.writer import AppendResult, StreamWriter

__all__ = [
    "AppendResult",
    "ContinuousQuery",
    "StreamWriter",
    "SubscriptionManager",
    "SubscriptionResult",
]
