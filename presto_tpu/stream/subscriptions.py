"""Continuous-query subscriptions over the serving layer.

A :class:`ContinuousQuery` is a standing statement: subscribed once
(``QueryServer.subscribe`` / ``POST /v1/subscribe``), prepared into a
plan template, then re-executed whenever a referenced table's version
epoch advances (streaming appends) or an interval tick elapses. Every
refresh flows through the server's normal admitted path — fair-slot
per tenant, in-flight accounting, and the ``TemplateBatchGate``, so N
same-template dashboards woken by one append stack their bindings
into ONE vmapped dispatch.

The :class:`SubscriptionManager`'s single notifier thread only
*detects* due work (epoch deltas, ticks); each due refresh executes
on its own short-lived thread so concurrent same-template refreshes
actually meet at the batch gate instead of serializing.

Freshness: the epoch snapshot is taken when the refresh FIRES, before
execution; the delivered :class:`SubscriptionResult` carries it. The
plan fingerprint folds live table versions, so the execution can
neither coalesce onto nor cache-hit any pre-append run — and delivery
re-asserts monotonicity (``subscription.stale_blocked``: always 0).

``mode="approx"`` subscriptions prepare against the server's sibling
approx session (``approx_join`` on, optionally sampled scans), whose
results arrive flagged ``approximate`` — never silently.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Mapping, Optional

from presto_tpu.runtime.errors import InternalError, PrestoError, UserError
from presto_tpu.runtime.metrics import REGISTRY


@dataclass(frozen=True)
class SubscriptionResult:
    """One delivered refresh. ``epochs`` is the per-table version
    snapshot taken at fire time — the rows reflect AT LEAST these
    versions (the freshness floor, not a ceiling: an append landing
    mid-execution may already be visible)."""

    df: object
    epochs: Mapping[str, int]
    seq: int
    trigger: str  # "initial" | "epoch" | "interval"
    approximate: bool
    batched: bool
    refresh_s: float


class ContinuousQuery:
    """The client-facing subscription surface: a bounded ring of
    delivered results plus wait/poll helpers. Delivery state is
    guarded by one condition variable; scheduling state (what is due,
    what is in flight) lives in the :class:`SubscriptionManager`."""

    def __init__(self, sub_id: str, sql: str, tenant: str, mode: str,
                 interval_s: Optional[float], tables: tuple,
                 keep: int = 8):
        self.id = sub_id
        self.sql = sql
        self.tenant = tenant
        self.mode = mode
        self.interval_s = interval_s
        #: tables the prepared plan scans (epoch-watched subset of
        #: these drives refreshes)
        self.tables = tuple(tables)
        self._cv = threading.Condition()
        self._results: "deque[SubscriptionResult]" = deque(maxlen=max(1, keep))
        self._seq = 0
        self._state = "ACTIVE"  # ACTIVE | CANCELLED | FAILED
        self._last_error: Optional[str] = None
        self._failures = 0  # consecutive refresh failures

    # ---- observation -----------------------------------------------------
    @property
    def state(self) -> str:
        with self._cv:
            return self._state

    @property
    def seq(self) -> int:
        with self._cv:
            return self._seq

    @property
    def last_error(self) -> Optional[str]:
        with self._cv:
            return self._last_error

    def latest(self) -> Optional[SubscriptionResult]:
        with self._cv:
            return self._results[-1] if self._results else None

    def results(self) -> "list[SubscriptionResult]":
        with self._cv:
            return list(self._results)

    def wait_for_seq(self, seq: int,
                     timeout_s: float = 30.0) -> SubscriptionResult:
        """Block until a result with sequence >= ``seq`` is delivered;
        raises (typed) on timeout, cancellation, or failure."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._seq >= seq or self._state != "ACTIVE",
                timeout_s)
            for r in self._results:
                if r.seq >= seq:
                    return r
            raise UserError(
                f"subscription {self.id}: no result with seq>={seq} "
                f"(state={self._state}, seq={self._seq}, "
                f"last_error={self._last_error})")

    def wait_for_epoch(self, table: str, epoch: int,
                       timeout_s: float = 30.0) -> SubscriptionResult:
        """Block until a delivered result reflects ``table`` at version
        >= ``epoch`` (the freshness floor a writer's
        :class:`~presto_tpu.stream.writer.AppendResult` hands out)."""
        def have():
            return (any(r.epochs.get(table, -1) >= epoch
                        for r in self._results)
                    or self._state != "ACTIVE")

        with self._cv:
            self._cv.wait_for(have, timeout_s)
            for r in self._results:
                if r.epochs.get(table, -1) >= epoch:
                    return r
            raise UserError(
                f"subscription {self.id}: no result at {table!r} epoch "
                f">={epoch} (state={self._state}, "
                f"last_error={self._last_error})")

    def page(self) -> dict:
        """The HTTP poll-page shape (``GET /v1/subscription/<id>``)."""
        with self._cv:
            p = {
                "id": self.id, "sql": self.sql, "tenant": self.tenant,
                "mode": self.mode, "state": self._state, "seq": self._seq,
                "tables": list(self.tables),
            }
            if self._last_error:
                p["error"] = self._last_error
            last = self._results[-1] if self._results else None
        if last is not None:
            from presto_tpu.server.frontend import _df_payload

            p["epochs"] = dict(last.epochs)
            p["trigger"] = last.trigger
            p["approximate"] = last.approximate
            p["refreshS"] = round(last.refresh_s, 6)
            p.update(_df_payload(last.df))
        return p

    # ---- delivery (manager-side) ----------------------------------------
    def _deliver(self, df, epochs: Mapping[str, int], trigger: str,
                 approximate: bool, batched: bool,
                 refresh_s: float) -> SubscriptionResult:
        with self._cv:
            prev = self._results[-1] if self._results else None
            if prev is not None and any(
                    epochs.get(t, 0) < e for t, e in prev.epochs.items()):
                # the freshness contract's last line of defense: a
                # refresh must never deliver an OLDER view than one
                # already served (fires are serialized per sub, so
                # reaching here is an engine bug, not a race)
                REGISTRY.counter("subscription.stale_blocked").add()
                raise InternalError(
                    f"subscription {self.id}: stale delivery "
                    f"{dict(epochs)} after {dict(prev.epochs)}")
            self._seq += 1
            res = SubscriptionResult(
                df=df, epochs=dict(epochs), seq=self._seq, trigger=trigger,
                approximate=approximate, batched=batched,
                refresh_s=refresh_s)
            self._results.append(res)
            self._failures = 0
            self._cv.notify_all()
        return res

    def _fail(self, exc: BaseException, typed: bool,
              max_failures: int) -> bool:
        """Record a refresh failure; returns True when the
        subscription transitioned to FAILED (untyped breach, or too
        many consecutive typed failures)."""
        with self._cv:
            self._last_error = f"{type(exc).__name__}: {exc}"
            self._failures += 1
            if not typed or self._failures >= max_failures:
                self._state = "FAILED"
            self._cv.notify_all()
            return self._state == "FAILED"

    def _cancel(self) -> None:
        with self._cv:
            if self._state == "ACTIVE":
                self._state = "CANCELLED"
            self._cv.notify_all()


class SubscriptionManager:
    """Owns every subscription of one :class:`QueryServer`: epoch
    watching, interval ticks, refresh dispatch, lifecycle."""

    #: idle poll cadence of the notifier thread; a write to any hooked
    #: connector wakes it immediately (Event.set from the DDL
    #: listener), so this only bounds interval-tick resolution
    POLL_S = 0.05
    #: consecutive TYPED refresh failures before a subscription is
    #: marked FAILED instead of retrying on the next epoch/tick —
    #: transient chaos faults must not kill a dashboard, a persistent
    #: failure must not retry forever
    MAX_CONSECUTIVE_FAILURES = 20

    def __init__(self, server):
        self._server = server
        self._lock = threading.Lock()
        self._subs: "dict[str, ContinuousQuery]" = {}
        #: manager-owned scheduling state per subscription id:
        #: session/prepared-key, epoch sources, last-fired epochs,
        #: pending/inflight flags, next interval tick
        self._sched: "dict[str, dict]" = {}
        self._hooked: "set[int]" = set()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._ids = itertools.count(1)

    # ---- registration ----------------------------------------------------
    def subscribe(self, sql: str, tenant: str, mode: str = "exact",
                  interval_s: Optional[float] = None,
                  keep: int = 8) -> ContinuousQuery:
        if mode not in ("exact", "approx"):
            raise UserError(f"subscription mode must be exact|approx, "
                            f"got {mode!r}")
        if interval_s is not None and interval_s <= 0:
            raise UserError(f"interval_s must be positive, got {interval_s}")
        session = (self._server.approx_session() if mode == "approx"
                   else self._server.session)
        sub_id = f"sub_{next(self._ids)}"
        key = f"{tenant}::{sub_id}"
        handle = session.prepare(sql, key)
        if handle.n_user:
            session._prepared.pop(key, None)
            raise UserError(
                "subscription SQL must not contain ? placeholders "
                "(literals are auto-templated; there is no per-refresh "
                "binding source)")
        from presto_tpu.cache.fingerprint import referenced_tables

        tables = tuple(t for _, t in referenced_tables(handle.plan))
        sources = self._epoch_sources(tables)
        sub = ContinuousQuery(sub_id, sql, tenant, mode, interval_s,
                              tables, keep=keep)
        with self._lock:
            self._subs[sub_id] = sub
            self._sched[sub_id] = {
                "session": session, "key": key, "sources": sources,
                "fired": {}, "pending": True, "inflight": False,
                "next_tick": (time.monotonic() + interval_s
                              if interval_s else None),
                # freshness baseline: creation counts as "delivered" so
                # lag measures refresh progress, not subscription age
                "delivered_mono": time.monotonic(),
            }
            for conn in sources.values():
                if id(conn) not in self._hooked:
                    # one listener per connector: any write wakes the
                    # notifier, which matches tables to subscriptions
                    conn.add_ddl_listener(self._on_write)
                    self._hooked.add(id(conn))
            if not self._running:
                self._running = True
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="presto-tpu-subscriptions")
                self._thread.start()
        REGISTRY.counter("subscription.created").add()
        self._wake.set()
        return sub

    def unsubscribe(self, sub_id: str) -> None:
        with self._lock:
            sub = self._subs.pop(sub_id, None)
            sched = self._sched.pop(sub_id, None)
        if sub is None:
            raise UserError(f"unknown subscription: {sub_id}")
        sub._cancel()
        if sched is not None:
            sched["session"]._prepared.pop(sched["key"], None)
        REGISTRY.counter("subscription.cancelled").add()

    def get(self, sub_id: str) -> ContinuousQuery:
        with self._lock:
            sub = self._subs.get(sub_id)
        if sub is None:
            raise UserError(f"unknown subscription: {sub_id}")
        return sub

    def snapshot(self) -> "list[dict]":
        with self._lock:
            subs = list(self._subs.values())
        return [s.page() for s in subs]

    def max_lag_s(self) -> float:
        """Worst delivery lag across ACTIVE subscriptions: seconds
        since the last delivery for any subscription with
        due-but-undelivered work (a pending or in-flight refresh).
        Idle subscriptions carry no lag — an unchanged table is not
        stale. 0.0 with no subscriptions. This is the freshness signal
        the health watchdog samples (runtime/health.py)."""
        now = time.monotonic()
        worst = 0.0
        with self._lock:
            for sid, sub in self._subs.items():
                sched = self._sched[sid]
                if sub.state != "ACTIVE":
                    continue
                if not (sched["pending"] or sched["inflight"]):
                    continue
                worst = max(worst, now - sched.get("delivered_mono", now))
        return worst

    def close(self) -> None:
        """Stop the notifier and cancel every subscription (the
        server's shutdown path). In-flight refreshes finish through
        the server's ordinary drain accounting."""
        with self._lock:
            self._running = False
            thread, self._thread = self._thread, None
            subs = list(self._subs.values())
            scheds = list(self._sched.values())
            self._subs.clear()
            self._sched.clear()
        self._wake.set()
        if thread is not None:
            thread.join(10)
        for sched in scheds:
            sched["session"]._prepared.pop(sched["key"], None)
        for sub in subs:
            sub._cancel()

    # ---- epoch watching --------------------------------------------------
    def _epoch_sources(self, tables) -> dict:
        """{table: connector} for every referenced table on a
        versioned (streamable) connector. Tables on static catalogs
        have no epochs — subscriptions over only those refresh on
        interval ticks alone."""
        out = {}
        for conn in self._server.session.catalog.connectors.values():
            if not hasattr(conn, "table_epoch"):
                continue
            for t in tables:
                if t in conn.tables():
                    out[t] = conn
        return out

    def _on_write(self, table: str) -> None:
        # runs inside the writer's DDL-listener fire: must be O(1) and
        # lock-free — the notifier thread does the table matching
        self._wake.set()

    # ---- the notifier loop -----------------------------------------------
    def _loop(self) -> None:
        while True:
            self._wake.wait(self.POLL_S)
            self._wake.clear()
            with self._lock:
                if not self._running:
                    return
                due = self._due_locked()
            # one thread per due refresh, started together: concurrent
            # same-template refreshes meet at the TemplateBatchGate
            # and stack into one vmapped dispatch
            for sub, sched, epochs, trigger in due:
                threading.Thread(
                    target=self._fire, args=(sub, sched, epochs, trigger),
                    daemon=True, name=f"presto-tpu-{sub.id}",
                ).start()

    def _due_locked(self):
        now = time.monotonic()
        due = []
        for sid, sub in self._subs.items():
            sched = self._sched[sid]
            if sched["inflight"] or sub.state != "ACTIVE":
                continue
            epochs = {t: conn.table_epoch(t)
                      for t, conn in sched["sources"].items()}
            trigger = None
            if sched["pending"]:
                trigger = "initial" if not sched["fired"] else "epoch"
            elif any(epochs[t] > sched["fired"].get(t, -1) for t in epochs):
                trigger = "epoch"
            elif (sched["next_tick"] is not None
                  and now >= sched["next_tick"]):
                trigger = "interval"
            if trigger is None:
                continue
            sched["pending"] = False
            sched["inflight"] = True
            # the freshness floor: epochs AS OF this fire decision —
            # the delivered result must reflect at least these
            sched["fired"] = dict(epochs)
            if sched["next_tick"] is not None:
                sched["next_tick"] = now + float(sub.interval_s)
            due.append((sub, sched, epochs, trigger))
        return due

    # ---- refresh execution -----------------------------------------------
    def _fire(self, sub: ContinuousQuery, sched: dict,
              epochs: "dict[str, int]", trigger: str) -> None:
        from presto_tpu.runtime.session import REQUEST_TRACE

        server = self._server
        #: links the refresh execution back to its subscription: the
        #: query runs with trace token ``sub:<id>`` and a stamped
        #: subscription_id (-> system.query_history), and writes its
        #: engine query id back for the post-hoc fire span below
        trace_ctx = {"token": f"sub:{sub.id}", "trace_id": "",
                     "subscription_id": sub.id, "force_trace": False}
        try:
            t0 = time.perf_counter()
            try:
                server._enter(sub.tenant)
            except UserError:
                # draining: the refresh is dropped, the subscription
                # stays ACTIVE (a restarted server re-fires it)
                REGISTRY.counter("subscription.drain_blocked").add()
                return
            try:
                try:
                    rt_token = REQUEST_TRACE.set(trace_ctx)
                    try:
                        df, info = server._execute_admitted(
                            lambda: sched["session"].execute_prepared(
                                sched["key"], []),
                            sub.tenant, timeout_s=server.submit_timeout_s)
                    finally:
                        REQUEST_TRACE.reset(rt_token)
                finally:
                    server._leave()
            except PrestoError as e:
                REGISTRY.counter("subscription.refresh_failed").add()
                failed = sub._fail(e, typed=True,
                                   max_failures=self.MAX_CONSECUTIVE_FAILURES)
                if not failed:
                    # the fire's epochs were NOT delivered: re-arm so
                    # the next pass retries (freshness over silence)
                    with self._lock:
                        if sub.id in self._sched:
                            sched["pending"] = True
                return
            except Exception as e:  # noqa: BLE001 — contract breach, recorded
                REGISTRY.counter("subscription.refresh_failed").add()
                sub._fail(e, typed=False,
                          max_failures=self.MAX_CONSECUTIVE_FAILURES)
                return
            dt = time.perf_counter() - t0
            sub._deliver(df=df, epochs=epochs, trigger=trigger,
                         approximate=bool(info.approximate),
                         batched=bool(info.batched), refresh_s=dt)
            with self._lock:
                if sub.id in self._sched:
                    sched["delivered_mono"] = time.monotonic()
            try:
                # child span on the refresh query's own recorder: the
                # fire (enter -> admitted -> delivered) wraps the
                # engine-side spans, so a trace export reads the
                # subscription wake as the parent of the execution
                if trace_ctx.get("query_id"):
                    tracer = sched["session"].traces.for_query(
                        trace_ctx["query_id"])
                    if tracer is not None:
                        tracer.add_complete(
                            "subscription:fire", "subscription", t0, dt,
                            {"subscriptionId": sub.id, "trigger": trigger,
                             "tenant": sub.tenant})
                slo = getattr(server.session, "slo", None)
                if slo is not None:
                    # the delivered refresh IS the freshness sample:
                    # fire-to-delivery wall time vs the objective
                    slo.observe_freshness(sub.tenant, dt)
            except Exception:  # noqa: BLE001 — observability-only path
                REGISTRY.counter("exec.trace_errors").add()
            REGISTRY.counter("subscription.fired").add()
            REGISTRY.counter(f"subscription.trigger.{trigger}").add()
            REGISTRY.histogram("subscription.refresh_s").add(dt)
        finally:
            with self._lock:
                sched["inflight"] = False
            # epochs may have advanced mid-refresh: re-check promptly
            self._wake.set()
