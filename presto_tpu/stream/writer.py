"""Micro-batch ingestion over the memory connector's append path.

``StreamWriter.append(table, micro_batch)`` is the ingest API: O(batch)
encode + concatenate on the connector (connectors/memory.py), exact
incremental stats merge, one version-epoch bump, and scoped cache
invalidation through the connector's DDL listeners — appending to
table A never evicts cached results that only touch table B. The
returned :class:`AppendResult` carries the post-append epoch, the
value continuous-query freshness is measured against.
"""

from __future__ import annotations

from dataclasses import dataclass

from presto_tpu.runtime.errors import UserError
from presto_tpu.runtime.metrics import REGISTRY


@dataclass(frozen=True)
class AppendResult:
    """One micro-batch landing: ``epoch`` is the table's version AFTER
    this append — any subscription refresh fired at or after this
    epoch reflects these rows."""

    table: str
    rows: int
    total_rows: int
    epoch: int
    created: bool


class StreamWriter:
    """Session-scoped ingest handle for one writable connector.

    Appends to a missing table create it (first micro-batch defines
    the schema; counted as ``stream.tables_created``). Appends within
    one writer serialize on the connector's write lock; run one writer
    per table for ordered epochs."""

    def __init__(self, session, connector: str = "memory"):
        self._session = session
        try:
            self._conn = session.catalog.connector(connector)
        except KeyError:
            raise UserError(f"unknown catalog: {connector}") from None
        for req in ("append", "create_table", "table_epoch", "row_count"):
            if not hasattr(self._conn, req):
                raise UserError(
                    f"catalog {connector!r} is not streamable: connector "
                    f"lacks {req}()"
                )

    def append(self, table: str, micro_batch) -> AppendResult:
        """Land one micro-batch (a pandas DataFrame); returns the
        :class:`AppendResult` with the post-append epoch."""
        with REGISTRY.histogram("stream.append_s").time():
            created = table not in self._conn.tables()
            if created:
                rows = self._conn.create_table(table, micro_batch)
                REGISTRY.counter("stream.tables_created").add()
            else:
                rows = self._conn.append(table, micro_batch)
        REGISTRY.counter("stream.appends").add()
        REGISTRY.counter("stream.rows").add(rows)
        return AppendResult(
            table=table,
            rows=rows,
            total_rows=self._conn.row_count(table),
            epoch=self._conn.table_epoch(table),
            created=created,
        )

    def epoch(self, table: str) -> int:
        """The table's current version epoch (0 = never written)."""
        return self._conn.table_epoch(table)
