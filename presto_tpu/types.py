"""Logical type system.

Reference parity: presto-common ``com.facebook.presto.common.type``
(``BigintType``, ``IntegerType``, ``DoubleType``, ``DecimalType``,
``VarcharType``, ``DateType``, ``BooleanType`` ... [SURVEY §2.1; reference
tree unavailable, paths reconstructed from the upstream prestodb layout]).

TPU-first physical mapping — every logical type maps onto a fixed-width
device representation so batches are struct-of-arrays `jnp` tensors:

=============  =========================================================
Logical        Physical (device)
=============  =========================================================
BOOLEAN        bool_
INTEGER        int32
BIGINT         int64  (XLA:TPU emulates s64; hot paths downcast when safe)
DOUBLE         float32 (TPU-native; exactness lives in DECIMAL, not FP)
DECIMAL(p,s)   int64 scaled by 10**s — exact arithmetic, exact sums
DATE           int32 days since 1970-01-01
TIMESTAMP      int64 microseconds since 1970-01-01 00:00:00 UTC
VARCHAR        int32 codes into an *ordered* host-side dictionary, so
               code comparison == lexicographic comparison (analog of
               the reference's DictionaryBlock, made order-preserving)
BYTES(w)       uint8[cap, w] fixed-width padded bytes — the raw-string
               representation for Pallas LIKE/substr kernels
=============  =========================================================

Deliberate cut — nested types (ARRAY/MAP/ROW) and UNNEST
--------------------------------------------------------
The reference's block model carries ArrayBlock/MapBlock/RowBlock and an
UnnestOperator [SURVEY §2.1]. None of the three target workloads
(TPC-H, TPC-DS, SSB) uses them, so this build cuts them rather than
shipping untested surface. The TPU-first design, should a connector
need them, is pinned down so the data model does not dead-end:

- ``ARRAY(T, max_len)``: SoA ``[cap, max_len]`` element tensor in T's
  physical dtype plus an int32 lengths vector (same pattern as BYTES'
  fixed width; stats pick max_len like they pick join-key bounds).
  Variable lengths beyond max_len overflow-flag and re-plan, exactly
  like capacity buckets (SURVEY §7.4 #1).
- ``MAP(K, V)``: two parallel ARRAY columns (sorted keys) — lookups are
  per-row vectorized binary probes on the key tensor.
- ``ROW(...)``: flattens into one physical column per field at scan
  time (a struct is just columns; only the analyzer sees the nesting).
- ``UNNEST``: row expansion with a static output capacity — the same
  expand-kernel shape as the duplicate-capable join probe
  (``ops.join.probe_expand``): output row i maps to (source_row,
  element_index) via cumsum of lengths, one gather per output column.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace

import jax.numpy as jnp
import numpy as np


class TypeKind(enum.Enum):
    BOOLEAN = "boolean"
    INTEGER = "integer"
    BIGINT = "bigint"
    DOUBLE = "double"
    DECIMAL = "decimal"
    DATE = "date"
    TIMESTAMP = "timestamp"  # int64 microseconds since the epoch
    VARCHAR = "varchar"  # ordered-dictionary-encoded string
    BYTES = "bytes"  # fixed-width raw bytes


@dataclass(frozen=True)
class DataType:
    """A logical SQL type plus the parameters that pin its physical layout.

    ``phys`` decouples the *physical* device dtype from the logical
    kind: a connector whose stats bound a column's value domain narrows
    its storage (BIGINT carried as int16, DECIMAL cents as int32, ...)
    — the HBM-bandwidth lever the bench measured at ~4x on Q1
    (notes/PERF.md §6-§8). The empty string means the canonical
    mapping below. Narrowed types ride Column/Batch pytree aux, so jit
    signatures key on the physical layout; the LOGICAL identity is the
    canonical form — ``common_super_type`` and every coercion resolve
    to canonical types, which is what makes arithmetic widen narrow
    reads before any overflow is possible (see ``canonical()``).
    """

    kind: TypeKind
    precision: int = 0  # DECIMAL precision
    scale: int = 0  # DECIMAL scale
    width: int = 0  # BYTES fixed width
    phys: str = ""  # physical dtype override (numpy name); "" = canonical

    # ---- physical layout ------------------------------------------------
    @property
    def np_dtype(self) -> np.dtype:
        if self.phys:
            return np.dtype(self.phys)
        return np.dtype(_PHYSICAL[self.kind])

    @property
    def jnp_dtype(self):
        if self.phys:
            return jnp.dtype(self.phys)
        return jnp.dtype(_PHYSICAL[self.kind])

    @property
    def canonical_np_dtype(self) -> np.dtype:
        return np.dtype(_PHYSICAL[self.kind])

    @property
    def is_narrowed(self) -> bool:
        return bool(self.phys)

    def canonical(self) -> "DataType":
        """The logical identity: this type with canonical storage."""
        return replace(self, phys="") if self.phys else self

    def with_physical(self, np_dtype) -> "DataType":
        """This type stored as ``np_dtype`` (None/canonical -> clears
        the override, keeping narrowed == canonical an impossibility
        for equal layouts)."""
        if np_dtype is None:
            return self.canonical()
        dt = np.dtype(np_dtype)
        if dt == self.canonical_np_dtype:
            return self.canonical()
        return replace(self, phys=dt.name)

    @property
    def is_string(self) -> bool:
        return self.kind in (TypeKind.VARCHAR, TypeKind.BYTES)

    @property
    def is_numeric(self) -> bool:
        return self.kind in (
            TypeKind.INTEGER,
            TypeKind.BIGINT,
            TypeKind.DOUBLE,
            TypeKind.DECIMAL,
        )

    @property
    def is_orderable(self) -> bool:
        return self.kind is not TypeKind.BYTES or self.width > 0

    # ---- value conversion ----------------------------------------------
    def to_physical(self, value):
        """Convert one Python-level value to its physical scalar."""
        if value is None:
            return self.null_value()
        if self.kind is TypeKind.DECIMAL:
            return int(round(float(value) * 10**self.scale))
        if self.kind is TypeKind.DATE:
            if isinstance(value, str):
                return (np.datetime64(value, "D") - np.datetime64("1970-01-01", "D")).astype(
                    np.int32
                )
            return int(value)
        if self.kind is TypeKind.TIMESTAMP:
            if isinstance(value, str):
                return int((np.datetime64(value.strip(), "us")
                            - np.datetime64("1970-01-01T00:00:00", "us"))
                           .astype(np.int64))
            return int(value)
        if self.kind is TypeKind.BOOLEAN:
            return bool(value)
        if self.kind in (TypeKind.INTEGER, TypeKind.BIGINT):
            return int(value)
        if self.kind is TypeKind.DOUBLE:
            return float(value)
        raise TypeError(f"cannot convert scalar for {self}")

    def from_physical(self, value):
        """Convert one physical scalar back to a Python-level value."""
        if self.kind is TypeKind.DECIMAL:
            return int(value) / 10**self.scale
        if self.kind is TypeKind.BOOLEAN:
            return bool(value)
        if self.kind is TypeKind.DOUBLE:
            return float(value)
        if self.kind is TypeKind.DATE:
            return str(np.datetime64("1970-01-01", "D") + np.int64(value))
        if self.kind is TypeKind.TIMESTAMP:
            return str(np.datetime64("1970-01-01T00:00:00", "us")
                       + np.timedelta64(int(value), "us"))
        return int(value)

    def null_value(self):
        """Physical fill value used in NULL slots (masked by validity)."""
        if self.kind is TypeKind.DOUBLE:
            return 0.0
        if self.kind is TypeKind.BOOLEAN:
            return False
        return 0

    def __str__(self) -> str:
        if self.kind is TypeKind.DECIMAL:
            return f"decimal({self.precision},{self.scale})"
        if self.kind is TypeKind.BYTES:
            return f"bytes({self.width})"
        return self.kind.value

    def physical_str(self) -> str:
        """Rendering with the physical storage made visible (EXPLAIN):
        ``bigint`` canonically, ``bigint:int16`` when narrowed."""
        base = str(self)
        return f"{base}:{self.phys}" if self.phys else base


_PHYSICAL = {
    TypeKind.BOOLEAN: np.bool_,
    TypeKind.INTEGER: np.int32,
    TypeKind.BIGINT: np.int64,
    TypeKind.DOUBLE: np.float32,
    TypeKind.DECIMAL: np.int64,
    TypeKind.DATE: np.int32,
    TypeKind.TIMESTAMP: np.int64,  # microseconds since epoch
    TypeKind.VARCHAR: np.int32,  # dictionary codes
    TypeKind.BYTES: np.uint8,
}

BOOLEAN = DataType(TypeKind.BOOLEAN)
INTEGER = DataType(TypeKind.INTEGER)
BIGINT = DataType(TypeKind.BIGINT)
DOUBLE = DataType(TypeKind.DOUBLE)
DATE = DataType(TypeKind.DATE)
TIMESTAMP = DataType(TypeKind.TIMESTAMP)


def decimal(precision: int, scale: int) -> DataType:
    return DataType(TypeKind.DECIMAL, precision=precision, scale=scale)


def varchar() -> DataType:
    return DataType(TypeKind.VARCHAR)


VARCHAR = varchar()


def fixed_bytes(width: int) -> DataType:
    return DataType(TypeKind.BYTES, width=width)


#: kinds whose physical storage may be narrowed from stats bounds —
#: fixed-point/integer representations where a narrower signed int is
#: value-identical. DOUBLE/BOOLEAN/BYTES never narrow.
NARROWABLE_KINDS = frozenset({
    TypeKind.INTEGER, TypeKind.BIGINT, TypeKind.DECIMAL, TypeKind.DATE,
    TypeKind.TIMESTAMP, TypeKind.VARCHAR,
})

_NARROW_LADDER = (np.int8, np.int16, np.int32, np.int64)


def narrow_physical(dtype: DataType, lo: int, hi: int) -> DataType:
    """The narrowest signed-int storage of ``dtype`` whose range covers
    the PHYSICAL-value interval [lo, hi] — scaled ints for DECIMAL, day
    numbers for DATE, dictionary codes for VARCHAR. Never wider than
    canonical, and never a dtype whose extreme the domain touches
    (``max(|lo|, |hi|) < 2^(bits-1)``), so unary negation of any
    in-domain value stays exact. Returns ``dtype`` unchanged for
    un-narrowable kinds or unbounded/oversized domains."""
    if dtype.kind not in NARROWABLE_KINDS or dtype.phys:
        return dtype
    lo, hi = int(lo), int(hi)
    if lo > hi:
        return dtype
    canonical_size = dtype.canonical_np_dtype.itemsize
    bound = max(abs(lo), abs(hi))
    for cand in _NARROW_LADDER:
        info = np.iinfo(cand)
        if np.dtype(cand).itemsize >= canonical_size:
            return dtype
        if bound < -int(info.min):  # strict: the extreme slot stays free
            return dtype.with_physical(cand)
    return dtype


def check_narrow_range(name: str, dtype: DataType, arr) -> None:
    """The narrow-storage soundness guard, shared by every host->device
    materialization site (Batch.from_numpy, the distributed scan):
    connector bounds are *declared*, so a value outside a narrowed
    column's physical dtype must fail LOUDLY here — assigning it into
    the narrow buffer would wrap silently."""
    if not dtype.is_narrowed or getattr(arr, "size", 0) == 0:
        return
    info = np.iinfo(dtype.np_dtype)
    lo, hi = arr.min(), arr.max()
    if lo < info.min or hi > info.max:
        raise ValueError(
            f"column {name!r}: value range [{lo}, {hi}] exceeds its "
            f"narrowed physical storage {dtype.np_dtype} — wrong/stale "
            "connector stats"
        )


def common_super_type(a: DataType, b: DataType) -> DataType:
    """Implicit-coercion lattice (reference: TypeCoercion in sql.analyzer).

    Resolves over the LOGICAL identities: narrowed physical storage
    never propagates through coercion — mixed-width operands meet in
    the canonical type, so comparisons/arithmetic widen narrow reads
    instead of truncating the wider side. (Two identically-narrowed
    types still meet in themselves via the ``a == b`` fast path, which
    is exact: same storage, same domain.)"""
    if a == b:
        return a
    a = a.canonical()
    b = b.canonical()
    if a == b:
        return a
    order = {
        TypeKind.INTEGER: 0,
        TypeKind.BIGINT: 1,
        TypeKind.DECIMAL: 2,
        TypeKind.DOUBLE: 3,
    }
    if a.kind in order and b.kind in order:
        hi = a if order[a.kind] >= order[b.kind] else b
        lo = b if hi is a else a
        if hi.kind is TypeKind.DECIMAL and lo.kind is TypeKind.DECIMAL:
            scale = max(a.scale, b.scale)
            prec = max(a.precision - a.scale, b.precision - b.scale) + scale
            return decimal(min(prec, 38), scale)
        return hi
    if a.kind is TypeKind.DATE and b.kind is TypeKind.DATE:
        return a
    # DATE widens to TIMESTAMP (midnight) when compared/combined
    if {a.kind, b.kind} == {TypeKind.DATE, TypeKind.TIMESTAMP}:
        return a if a.kind is TypeKind.TIMESTAMP else b
    # a string literal (VARCHAR) coerces to the peer fixed-width BYTES
    # type (coalesce(bytes_col, '') — the literal is space-padded)
    if a.kind is TypeKind.BYTES and b.kind is TypeKind.VARCHAR:
        return a
    if b.kind is TypeKind.BYTES and a.kind is TypeKind.VARCHAR:
        return b
    raise TypeError(f"no common super type for {a} and {b}")
