"""Hand-built physical plans for the benchmark workloads.

Reference parity: ``presto-benchmark``'s hand-built operator pipelines
(``HandTpchQuery1`` / ``HandTpchQuery6`` [SURVEY §6]) — the same role:
benchmark the operator/kernel layer without the SQL frontend. Shared by
tests, ``bench.py`` and ``__graft_entry__.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.operators import (
    AggSpec,
    DirectStrategy,
    FilterProjectOperator,
    HashAggregationOperator,
)
from presto_tpu.exec.pipeline import Pipeline, ScanSource
from presto_tpu.expr import Call, col, evaluate, evaluate_predicate, lit
from presto_tpu.ops.groupby import fused_small_sums, group_ids_direct
from presto_tpu.types import BIGINT, BOOLEAN, DATE, decimal, varchar

dec2 = decimal(12, 2)
dec4 = decimal(38, 4)

Q1_COLS = [
    "l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
    "l_discount", "l_tax", "l_shipdate",
]
Q1_CUTOFF = "1998-09-02"  # date '1998-12-01' - interval '90' day
Q1_GROUPS = 6  # |returnflag| x |linestatus| = 3 x 2


def q1_exprs():
    one = lit(1, dec2)
    disc_price = Call(
        dec4, "mul",
        (col("l_extendedprice", dec2), Call(dec2, "sub", (one, col("l_discount", dec2)))),
    )
    charge = Call(dec4, "mul", (disc_price, Call(dec2, "add", (one, col("l_tax", dec2)))))
    pred = Call(BOOLEAN, "le", (col("l_shipdate", DATE), lit(Q1_CUTOFF, DATE)))
    return pred, disc_price, charge


# Per-row |value| bit bounds from the TPC-H spec (§4.2.3 data ranges):
# quantity <= 50.00 (scaled 5e3 -> 13 bits), extendedprice <= ~105k
# (scaled ~1.05e7 -> 24 bits), disc_price/charge at scale 4 <= ~1.2e9
# (31 bits), discount <= 0.10 (scaled 10 -> 4 bits; 7 declared to match
# the kernel's one-lane [0, 100] guard). Bounds feed the lane-split
# aggregation (fewer passes).
Q1_BITS = {"sum_qty": 13, "sum_base_price": 24, "sum_disc_price": 31,
           "sum_charge": 31, "sum_disc": 7}


def q1_aggs():
    _, disc_price, charge = q1_exprs()
    return [
        AggSpec("sum", col("l_quantity", dec2), "sum_qty", decimal(38, 2),
                value_bits=Q1_BITS["sum_qty"]),
        AggSpec("sum", col("l_extendedprice", dec2), "sum_base_price",
                decimal(38, 2), value_bits=Q1_BITS["sum_base_price"]),
        AggSpec("sum", disc_price, "sum_disc_price", dec4,
                value_bits=Q1_BITS["sum_disc_price"]),
        AggSpec("sum", charge, "sum_charge", dec4,
                value_bits=Q1_BITS["sum_charge"]),
        AggSpec("count_star", None, "count_order", BIGINT),
    ]


def q1_strategy() -> DirectStrategy:
    return DirectStrategy((0, 0), (2, 1), Q1_GROUPS)


def q1_pipeline(conn: TpchConnector):
    pred, _, _ = q1_exprs()
    return Pipeline(
        ScanSource(conn, "lineitem", Q1_COLS),
        [
            FilterProjectOperator(pred, None),
            HashAggregationOperator(
                [("l_returnflag", col("l_returnflag", varchar())),
                 ("l_linestatus", col("l_linestatus", varchar()))],
                q1_aggs(), q1_strategy(),
            ),
        ],
    )


# ---------------------------------------------------------------------------
# The fused single-step form: one traced function Batch -> state.
# This is the engine's "forward step": what per-query JIT compilation
# produces for the leaf fragment of Q1 (scan -> filter -> partial agg).
# ---------------------------------------------------------------------------


def q1_fused_step(batch: Batch, pallas_ok: bool | None = None):
    """One fully-fused Q1 partial-aggregation step over a batch.

    Returns a dict of [6]-arrays: sums per (returnflag x linestatus)
    group plus the group-present mask and row count. All four sums, the
    count, and presence ride ONE ``fused_small_sums`` einsum — a single
    pass over the data (the MXU one-hot segment-sum), replacing the
    G x lanes masked-reduction passes of round 2. ``value_overflow``
    guards the declared Q1_BITS bounds at runtime.

    ``pallas_ok``: hoisted Pallas decision. Callers tracing this step
    inside jit/shard_map MUST pass it — ``pallas_q1.supported``'s
    shared-mask identity check is only sound on concrete batches
    (pytree flattening gives distinct tracers in-trace).
    """
    from presto_tpu.ops import pallas_q1
    from presto_tpu.ops.strings import use_pallas

    if pallas_ok is None:
        pallas_ok = (use_pallas() and jax.default_backend() == "tpu"
                     and pallas_q1.supported(batch)
                     and pallas_q1.probe_supported(batch.capacity))
    if pallas_ok:
        # HandTpchQuery1 fast path: the whole fragment as one Pallas
        # pass (predicate, gid, decimals, lane split, segment sums in
        # VMEM — ops/pallas_q1.py). Narrow-storage TPU batches only;
        # everything else takes the generic route below.
        return pallas_q1.q1_step(batch)

    pred, disc_price, charge = q1_exprs()
    live = batch.live & evaluate_predicate(pred, batch)
    gids, _ = group_ids_direct(
        [batch["l_returnflag"].data, batch["l_linestatus"].data],
        (0, 0), (2, 1), live, Q1_GROUPS,
    )
    qty = batch["l_quantity"].data
    ep = batch["l_extendedprice"].data
    disc = batch["l_discount"].data
    dp = evaluate(disc_price, batch).data
    ch = evaluate(charge, batch).data
    names = ["sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
             "sum_disc"]
    sums, counts, _, oflow = fused_small_sums(
        [qty, ep, dp, ch, disc],
        [Q1_BITS[n] for n in names],
        [live] * 5,
        gids,
        Q1_GROUPS,
    )
    out = dict(zip(names, sums))
    out["present"] = counts[0] > 0
    out["count_order"] = counts[0]
    out["value_overflow"] = oflow
    return out


def combine_q1_states(a: dict, b: dict) -> dict:
    bool_keys = ("present", "value_overflow")
    out = {k: a[k] + b[k] for k in a if k not in bool_keys}
    for k in bool_keys:
        out[k] = a[k] | b[k]
    return out


# ---------------------------------------------------------------------------
# Distributed Q1: data-parallel partial agg + psum final combine.
# The minimal real multi-chip fragment step (SURVEY §2.4 DP row).
# ---------------------------------------------------------------------------


def q1_distributed_step(mesh):
    """Returns a jitted SPMD step: sharded Batch -> replicated Q1 state.

    Rows are sharded over the worker axes (each device holds its scan
    partition; a dcn/ici mesh shards over both axes); partial
    aggregation runs per device; the final combine is a ``psum`` over
    the axes — the degenerate (6-group) case of the
    partitioned-exchange final aggregation.
    """
    from presto_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    from presto_tpu.parallel.mesh import worker_axes

    axes = worker_axes(mesh)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axes),),
        out_specs=P(),
        check_vma=False,
    )
    def step(batch: Batch):
        state = q1_fused_step(batch)

        def allreduce(x):
            if x.dtype == jnp.bool_:
                return jax.lax.psum(x.astype(jnp.int32), axes) > 0
            return jax.lax.psum(x, axes)

        return jax.tree.map(allreduce, state)

    return jax.jit(step)


def q1_batch(conn: TpchConnector, split=None, capacity=None) -> Batch:
    splits = conn.splits("lineitem")
    s = split if split is not None else splits[0]
    return conn.scan(s, Q1_COLS, capacity)
