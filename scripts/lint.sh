#!/usr/bin/env bash
# Static-analysis gate (tier-1 gate 12): the engine-invariant linter
# (presto_tpu/analysis/) in clean mode, PLUS a seeded-violation
# self-test proving the gate can actually fail — a lint gate that
# can't detect its own fixture violations is green paint.
#
#   1. `python -m presto_tpu.analysis` over the repo must exit 0
#      (every finding fixed, suppressed-with-reason, or baselined
#      with a reviewed justification).
#   2. Each of the four rule families must flag a seeded known-bad
#      fixture (one per family) written to a temp dir; a family that
#      goes silent fails the gate.
set -o pipefail
cd "$(dirname "$0")/.."

python -m presto_tpu.analysis "$@" || exit $?

python - <<'PY' || exit $?
import sys
import tempfile
from pathlib import Path

from presto_tpu.analysis import analyze

SEEDS = {
    "PT101": (
        "trace_mod.py",
        "import jax\n\n\n"
        "def _make_step():\n"
        "    def step(batch):\n"
        "        return int(batch['n'])\n"
        "    return jax.jit(step)\n"),
    "PT201": (
        "cache_mod.py",
        "import os\n\n"
        "from presto_tpu.cache.exec_cache import EXEC_CACHE\n\n\n"
        "def build():\n"
        "    def builder():\n"
        "        f = os.environ.get('PRESTO_TPU_SEEDED', '0')\n"
        "        return lambda b: b if f == '1' else -b\n"
        "    return EXEC_CACHE.get_or_build(\n"
        "        EXEC_CACHE.key_of('unrelated_tag', 1), builder)\n"),
    "PT301": (
        "lock_mod.py",
        "import threading\n\n\n"
        "class Shared:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._items = []\n\n"
        "    def add(self, x):\n"
        "        with self._lock:\n"
        "            self._items.append(x)\n\n"
        "    def drop(self, x):\n"
        "        self._items.remove(x)\n"),
    "PT401": (
        "test_env_mod.py",
        "import os\n\n\n"
        "def test_seeded():\n"
        "    os.environ['PRESTO_TPU_SEEDED'] = '1'\n"),
}

with tempfile.TemporaryDirectory() as td:
    root = Path(td)
    for rule, (name, src) in SEEDS.items():
        (root / name).write_text(src)
    res = analyze([td], root=td, baseline=[])
    found = {f.rule for f in res.findings}
    missing = sorted(set(SEEDS) - found)
    if missing:
        print("lint gate self-test FAILED: rule families went silent "
              f"on their seeded violations: {missing}", file=sys.stderr)
        sys.exit(1)
    print("lint gate: repo clean, all %d seeded rule families flagged "
          "(%s)" % (len(SEEDS), ", ".join(sorted(SEEDS))))
PY
