#!/usr/bin/env bash
# Tier-1 verify — the checked-in form of the ROADMAP.md command.
#
# Four gates, cheapest first:
#   1. `python -m compileall` over the package: a syntax/static gate
#      that fails in seconds instead of letting a typo ride to the
#      middle of the pytest run.
#   2. Cache cold-vs-warm smoke: one TPC-H aggregation twice in one
#      session, then once more in a fresh session — the warm runs must
#      hit the result cache and the executable cache with ZERO
#      re-traces and identical rows (ISSUE-2 acceptance).
#   3. Trace-export smoke: one distributed TPC-H query on an 8-device
#      virtual mesh must export valid Chrome-trace JSON with >= 1 span
#      per executed plan node and nonzero exchange bytes (ISSUE-3
#      acceptance).
#   4. Chaos smoke: a fixed-seed slice of the chaos suite (randomized
#      fault schedules incl. the backend-shaped `oom` kind) — every
#      round must match the fault-free oracle or fail with a TYPED
#      error, with zero memory-pool reservation leaks (ISSUE-4
#      acceptance).
#   5. Narrowing smoke: one fixed query with stats-driven narrow
#      physical storage ON vs OFF must return identical rows, the
#      narrow plan must route TPC-H Q1 through the fused-fragment
#      kernel path, and a warm narrow repeat must re-trace ZERO steps
#      (fingerprints carry the physical dtypes — ISSUE-5 acceptance).
#   6. Join smoke: TPC-H Q3 with runtime join filters on vs off must
#      return identical rows, the fused Pallas join route must fire
#      with measured probe-scan pruning, and a warm repeat must
#      re-trace ZERO steps (ISSUE-7 acceptance).
#   7. Observability smoke: the OpenMetrics exposition must parse with
#      known counters present, EXPLAIN ANALYZE on TPC-H Q3 must render
#      per-node est->actual with misestimate flags, system.plan_stats
#      must populate after a tracked query and invalidate after DDL,
#      and the fixed-seed sustained-load smoke must complete with a
#      drained pool under the no-hang contract (ISSUE-8 acceptance).
#   8. Leaf-route smoke: the generalized fused-leaf framework must
#      route SQL-path TPC-H Q6 AND an SSB Q1-flight leaf (membership
#      join folded) with rows identical to the generic route and ZERO
#      warm re-traces, and the adaptive partial-aggregation bypass
#      must trigger on a high-cardinality synthetic GROUP BY and be
#      recorded in system.plan_stats (ISSUE-9 acceptance).
#   9. Plan-template smoke: a TPC-H template executed at 3 different
#      literal bindings must re-trace ZERO jitted steps after the
#      first, return rows identical to the unparameterized
#      (plan_templates=0) run, PREPARE/EXECUTE ... USING must bind
#      correctly, and the global memory pool must drain to zero
#      (ISSUE-10 acceptance).
#  10. Flight-recorder smoke: a zipfian distributed repartition must
#      populate exchange.skew and render a >2x partition-skew ratio in
#      EXPLAIN ANALYZE (balanced stays ~1x); an injected fault must
#      auto-capture a post-mortem that round-trips through JSON export
#      with plan render + spans + metric delta; a warm template re-run
#      must show system.exec_cache hits with compile_s_saved > 0; the
#      global pool must drain (ISSUE-12 acceptance).
#  11. Serving smoke: the in-process multi-tenant server — concurrent
#      clients across two tenants through the fairness scheduler, the
#      /metrics exposition parses, an over-quota tenant stays bounded
#      at its concurrency cap, cross-query batched dispatch fires at
#      least once with results identical to serial execution, and the
#      global memory pool drains (ISSUE-14 acceptance).
#  12. Out-of-core spill smoke: a TPC-H join whose build side is ~4x
#      over `join_build_budget_bytes` must execute through the PLANNED
#      hybrid tier — `spill.planned_hybrid` fires, `query.oom_degraded`
#      stays ZERO (no ladder round-trip), EXPLAIN renders the spill
#      decision, rows are identical to the unconstrained run, and both
#      the memory pool and the host-spill budget drain to zero
#      (ISSUE-16 acceptance; the static gate below keeps the spill
#      code PT-lint green).
#  13. Streaming smoke: micro-batch appends through StreamWriter bump
#      the table epoch and re-fire continuous subscriptions with FRESH
#      rows (fire-time epochs delivered with every result), a
#      synchronized same-template refresh burst fuses at the batch
#      gate (deterministic hold, as in gate 11), and warm refreshes
#      re-trace ZERO jitted steps — the epoch bump invalidates
#      results, never executables (ISSUE-17 acceptance).
#  14. Health-observability smoke: an HTTP-submitted query carrying a
#      client W3C traceparent must echo the same trace-id back and
#      export ONE linked trace from frontend:submit through admission
#      and the batch-gate wait to the device steps and frontend:poll;
#      system.device_stats must populate (CPU-safe rows); the armed
#      watchdog on a quiet baseline must trip ZERO breaches; a seeded
#      latency regression must trip EXACTLY ONE health_breach carrying
#      a complete flight-record post-mortem of the worst in-flight
#      query; the server must drain clean (ISSUE-18 acceptance).
#  15. Overload smoke: under a deterministic 4x submit storm the
#      load-shedding server's goodput (completed within deadline) must
#      be >= the no-shed server's with every refusal the typed
#      retryable 429 ServerOverloaded; a seeded health breach must
#      flip brown-out-eligible tenants to the approx/shed tier and
#      recovery must re-arm exact service; DELETE of a RUNNING query
#      must free its reservations at the next cancel checkpoint; the
#      global pool must drain (ISSUE-19 acceptance).
#  16. Adaptivity smoke: a recurring zipf-skewed repartition join must
#      be rewritten with skew salting from plan-stats history — rows
#      bit-identical to the non-adaptive baseline on every run, EXPLAIN
#      rendering `repartition=salted(S)`, measured post-adaptation
#      exchange skew under 2x, the decision logged in system.adaptive;
#      the serving warmer must keep a warm serving window at ZERO cold
#      compiles; the global pool must drain (ISSUE-20 acceptance).
#  17. Static-analysis gate (scripts/lint.sh): the engine-invariant
#      linter (`python -m presto_tpu.analysis` — trace hygiene,
#      cache-key completeness, lock discipline, global-state hygiene)
#      must exit 0 on the repo, AND each rule family must flag its
#      seeded known-bad fixture — proving the gate can actually fail
#      (ISSUE-15 acceptance).
#  18. The tier-1 pytest suite on the CPU backend (virtual-device
#      distributed tests included; `slow` marks excluded), with the
#      same flags and timeout the driver uses.
#
# Exit status is the pytest status (or the compileall status when the
# static gate fails); DOTS_PASSED echoes the passed-test count the
# driver greps for.
set -o pipefail
cd "$(dirname "$0")/.."

python -m compileall -q presto_tpu || exit $?

timeout -k 10 240 env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python - <<'PY' || exit $?
import sys

sys.path.insert(0, ".")
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

conn = TpchConnector(sf=0.005)
q = ("select l_returnflag, l_linestatus, count(*) c, sum(l_quantity) q "
     "from lineitem group by l_returnflag, l_linestatus "
     "order by l_returnflag, l_linestatus")
s = Session({"tpch": conn})
a = s.sql(q)
t0 = REGISTRY.snapshot().get("exec.traces", 0)
b = s.sql(q)
snap = REGISTRY.snapshot()
assert snap.get("exec.traces", 0) == t0, "warm run re-traced"
assert snap.get("result_cache.hit", 0) >= 1, "no result-cache hit"
s2 = Session({"tpch": conn}, properties={"result_cache_enabled": False})
c = s2.sql(q)
snap2 = REGISTRY.snapshot()
assert snap2.get("exec_cache.hit", 0) >= 1, "no executable-cache hit"
assert snap2.get("exec.traces", 0) == t0, "cross-session run re-traced"
assert a.equals(b) and a.equals(c), "cached results differ"
print("cache smoke: exec_cache.hit=%d result_cache.hit=%d traces=%d"
      % (snap2.get("exec_cache.hit", 0), snap2.get("result_cache.hit", 0),
         snap2.get("exec.traces", 0)))
PY

timeout -k 10 420 env JAX_ENABLE_X64=1 python - <<'PY' || exit $?
import json
import sys

sys.path.insert(0, ".")
from __graft_entry__ import _provision_virtual_mesh

_provision_virtual_mesh(8)

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch.queries import QUERIES
from presto_tpu.parallel.mesh import make_mesh
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

s = Session({"tpch": TpchConnector(sf=0.005)}, mesh=make_mesh(8),
             trace_token="tier1-smoke")
df = s.sql(QUERIES["q3"])
assert len(df) > 0, "distributed Q3 produced no rows"
path = s.export_trace("/tmp/_t1_trace.json")
data = json.load(open(path))  # must be valid JSON
spans = [e for e in data["traceEvents"] if e.get("ph") == "X"]
assert spans, "empty trace"
assert all(e["args"].get("trace_token") == "tier1-smoke" for e in spans), \
    "trace_token missing from spans"
node_ids = {e["args"]["plan_node_id"] for e in spans
            if e.get("cat") == "node"}
plan = s.plan(QUERIES["q3"])

def count(n):
    return 1 + sum(count(c) for c in n.children)

want = count(plan)
assert len(node_ids) >= want, \
    f"only {len(node_ids)} node spans for {want} plan nodes"
ex_bytes = sum(e["args"].get("bytes", 0) for e in spans
               if e.get("cat") == "exchange")
assert ex_bytes > 0, "no exchange bytes recorded for a distributed run"
assert REGISTRY.snapshot().get("exchange.bytes", 0) > 0
print("trace smoke: %d spans, %d plan nodes, %d exchange bytes"
      % (len(spans), want, ex_bytes))
PY

timeout -k 10 480 env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python - <<'PY' || exit $?
import sys

sys.path.insert(0, ".")
sys.path.insert(0, "tests")
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.memory import global_pool
from test_chaos import build_oracle, run_chaos_round

conn = TpchConnector(sf=0.005)
oracle = build_oracle(conn)
# fixed seeds: deterministic schedules (query + session props + faults
# all derive from the seed; probability faults draw from the
# injector's own seeded stream). Each round asserts correct-or-typed,
# a bounded wall, and a drained pool.
outcomes = [run_chaos_round(conn, oracle, seed) for seed in range(10)]
assert global_pool().reserved_bytes == 0, "global pool reservation leak"
ok = sum(o.startswith("ok:") for o in outcomes)
assert ok >= 1, outcomes
print("chaos smoke: %d/%d correct, %d typed failures, pool balance 0"
      % (ok, len(outcomes), len(outcomes) - ok))
PY

timeout -k 10 300 env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python - <<'PY' || exit $?
import os
import sys

sys.path.insert(0, ".")
os.environ.pop("PRESTO_TPU_NARROW", None)
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch.queries import QUERIES
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

conn = TpchConnector(sf=0.005)
q = QUERIES["q1"]
s_on = Session({"tpch": conn}, properties={"result_cache_enabled": False})
a = s_on.sql(q)
assert REGISTRY.snapshot().get("exec.q1_fused_route", 0) >= 1, \
    "narrow Q1 did not route through the fused fragment kernel path"
t0 = REGISTRY.snapshot().get("exec.traces", 0)
b = s_on.sql(q)
t1 = REGISTRY.snapshot().get("exec.traces", 0)
assert t1 == t0, f"warm narrow repeat re-traced ({t1 - t0} new traces)"
s_off = Session({"tpch": conn}, properties={"narrow_storage": False,
                                            "result_cache_enabled": False})
c = s_off.sql(q)
os.environ.pop("PRESTO_TPU_NARROW", None)
assert a.equals(b) and a.equals(c), "narrowing on/off results differ"
print("narrowing smoke: on/off identical, fused Q1 route hit, "
      "0 warm re-traces")
PY

timeout -k 10 300 env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python - <<'PY' || exit $?
# Join smoke (ISSUE-7 acceptance): TPC-H Q3 with runtime join filters
# ON vs OFF must return identical rows, the fused Pallas join route
# must fire (exec.pallas_join_route) with measured scan pruning, and a
# warm repeat must re-trace ZERO steps. Session-property driven — the
# process-global env vars (PRESTO_TPU_NARROW) are left exactly as
# found (the tests/test_narrowing.py env-restore discipline).
import os
import sys

sys.path.insert(0, ".")
os.environ.pop("PRESTO_TPU_NARROW", None)
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch.queries import QUERIES
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

conn = TpchConnector(sf=0.005)
q = QUERIES["q3"]
s_on = Session({"tpch": conn}, properties={"result_cache_enabled": False})
a = s_on.sql(q)
snap = REGISTRY.snapshot()
assert snap.get("exec.pallas_join_route", 0) >= 1, \
    "Q3 did not hit the fused Pallas join route"
assert snap.get("join.filter_rows_pruned", 0) > 0, \
    "runtime join filters pruned no probe rows"
t0 = snap.get("exec.traces", 0)
b = s_on.sql(q)
t1 = REGISTRY.snapshot().get("exec.traces", 0)
assert t1 == t0, f"warm join repeat re-traced ({t1 - t0} new traces)"
s_off = Session({"tpch": conn}, properties={
    "result_cache_enabled": False, "runtime_join_filters": False,
    "pallas_join": False})
c = s_off.sql(q)
assert a.equals(b) and a.equals(c), \
    "runtime filters / fused kernel changed Q3 results"
print("join smoke: filters on/off identical, pallas route hit, "
      "%d rows pruned, 0 warm re-traces"
      % int(REGISTRY.snapshot().get("join.filter_rows_pruned", 0)))
PY

timeout -k 10 420 env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python - <<'PY' || exit $?
# Observability smoke (ISSUE-8 acceptance): estimate-vs-actual
# telemetry end to end + metrics exposition + the sustained-load
# harness, all on fixed seeds.
import re
import sys

sys.path.insert(0, ".")
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.session import Session
from presto_tpu.connectors.tpch.queries import QUERIES

conn = TpchConnector(sf=0.005)
s = Session({"tpch": conn}, properties={"result_cache_enabled": False})

# 1) EXPLAIN ANALYZE Q3: every executed node renders `est E->A (Nx)`,
#    misestimates are flagged, joins carry their chosen strategy
out = s.explain_analyze(QUERIES["q3"])
assert re.search(r"est [\d,]+->[\d,]+ \(", out), out
assert "MISEST" in out, "no misestimate flagged on Q3 (estimates are /3 and /8 guesses — silence means the flag is broken)"
assert "strategy=" in out, out

# 2) system.plan_stats: fingerprint-keyed history populated by the run
ps = s.sql("select fingerprint, node_type, est_rows, actual_rows, "
           "misest from plan_stats")
assert len(ps) > 0, "plan_stats empty after a tracked query"
assert ps["fingerprint"].str.len().eq(64).all()

# 3) DDL invalidation: history for a table dropped on its version bump
s.sql("create table t1obs as select l_orderkey, l_quantity "
      "from lineitem where l_quantity < 5")
s.execute("select count(*) c from t1obs")
n = len(s.plan_stats)
s.sql("insert into t1obs select l_orderkey, l_quantity "
      "from lineitem where l_quantity > 49")
assert len(s.plan_stats) == n - 1, "DDL did not invalidate plan_stats"

# 4) metrics exposition: parses line-by-line, known counters present
text = s.export_metrics()
lines = text.splitlines()
assert lines[-1] == "# EOF"
sample = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*(\{quantile="0\.\d+"\})? '
                    r'-?\d+(\.\d+)?(e-?\d+)?$')
names = set()
for line in lines[:-1]:
    if line.startswith("# TYPE ") or line.startswith("# HELP "):
        continue
    assert sample.match(line), f"unparseable exposition line: {line!r}"
    names.add(line.split("{")[0].split(" ")[0])
for want in ("presto_tpu_query_completed_total",
             "presto_tpu_exec_traces_total",
             "presto_tpu_plan_stats_recorded_total"):
    assert want in names, f"{want} missing from exposition"

# 5) fixed-seed sustained-load smoke (chaos variant): completes under
#    the no-hang contract with a drained pool and typed-only failures
from bench import run_sustained_load
from presto_tpu.runtime.memory import global_pool

res = run_sustained_load(n_sessions=2, duration_s=2.0, seed=0,
                         sf=0.002, chaos=True)
assert res["queries_ok"] > 0, res
assert res["pool_drained"], "sustained load leaked pool reservations"
assert not res["untyped_failures"], res["untyped_failures"]
assert res["chaos_rounds"] >= 1, res
assert global_pool().reserved_bytes == 0, "global pool reservation leak"
print("observability smoke: est->actual+MISEST rendered, %d plan_stats "
      "rows, DDL invalidation ok, exposition %d families, sustained "
      "load %.1f q/s p99 %.0fms (%d chaos rounds)"
      % (len(ps), len(names), res["queries_per_sec"],
         res["latency_p99_ms"], res["chaos_rounds"]))
PY

timeout -k 10 300 env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python - <<'PY' || exit $?
# Leaf-route smoke (ISSUE-9 acceptance): generalized fused-leaf route
# on Q6 + SSB Q1.1, on/off identical rows, 0 warm re-traces, adaptive
# partial-agg bypass on a high-cardinality GROUP BY recorded in
# system.plan_stats. Env left exactly as found (narrowing discipline).
import os
import sys

sys.path.insert(0, ".")
os.environ.pop("PRESTO_TPU_NARROW", None)
from presto_tpu.connectors.ssb import SsbConnector
from presto_tpu.connectors.ssb.queries import QUERIES as SSB
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch.queries import QUERIES as TPCH
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

tconn = TpchConnector(sf=0.005)
sconn = SsbConnector(sf=0.005)
s_on = Session({"tpch": tconn, "ssb": sconn},
               properties={"result_cache_enabled": False})
s_off = Session({"tpch": tconn, "ssb": sconn},
                properties={"result_cache_enabled": False,
                            "narrow_storage": False})
routed = 0
for q in (TPCH["q6"], SSB["q1_1"]):
    before = REGISTRY.snapshot().get("exec.leaf_fused_route", 0)
    a = s_on.sql(q)
    hits = REGISTRY.snapshot().get("exec.leaf_fused_route", 0) - before
    assert hits == 1, f"leaf fragment did not route (hits={hits})"
    routed += hits
    t0 = REGISTRY.snapshot().get("exec.traces", 0)
    b = s_on.sql(q)
    t1 = REGISTRY.snapshot().get("exec.traces", 0)
    assert t1 == t0, f"warm leaf-route repeat re-traced ({t1 - t0})"
    c = s_off.sql(q)
    os.environ.pop("PRESTO_TPU_NARROW", None)
    assert a.equals(b) and a.equals(c), "leaf route on/off results differ"
# adaptive bypass: near-unique key (exact NDV from the memory
# connector's store-time stats) -> agg_strategy=bypass, visible in
# EXPLAIN, counted, and recorded in system.plan_stats
s_on.sql("create table t9leaf as select l_orderkey * 10 + l_linenumber k,"
         " l_quantity v from lineitem")
bq = "select k, sum(v) s, count(*) c from t9leaf group by k"
before = REGISTRY.snapshot().get("agg.strategy.bypass", 0)
s_on.execute(bq)
assert REGISTRY.snapshot().get("agg.strategy.bypass", 0) == before + 1, \
    "high-cardinality GROUP BY did not bypass partial aggregation"
assert "agg_strategy=bypass" in s_on.explain(bq)
ps = s_on.sql("select node_type, strategy from plan_stats"
              " where strategy = 'bypass'")
assert len(ps) >= 1, "bypass strategy not recorded in system.plan_stats"
fb = {k: v for k, v in REGISTRY.snapshot().items()
      if k.startswith("exec.leaf_route_fallback")}
print("leaf-route smoke: %d fragments routed (q6 + ssb q1_1), on/off "
      "identical, 0 warm re-traces, bypass recorded in plan_stats, "
      "fallbacks=%s" % (routed, fb or "{}"))
PY

timeout -k 10 300 env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python - <<'PY' || exit $?
# Plan-template smoke (ISSUE-10 acceptance): one compiled executable
# serves every literal binding of a TPC-H template — the exec cache
# AND jax's signature cache hit across differing constants.
import sys

sys.path.insert(0, ".")
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.memory import global_pool
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

conn = TpchConnector(sf=0.005)
tpl = ("select o_orderpriority, count(*) c from lineitem"
       " join orders on l_orderkey = o_orderkey"
       " where l_quantity < {} group by o_orderpriority"
       " order by o_orderpriority")
s = Session({"tpch": conn}, properties={"result_cache_enabled": False})
s.sql(tpl.format(10))  # cold: trace + compile the template once
t0 = REGISTRY.snapshot().get("exec.traces", 0)
res = {v: s.sql(tpl.format(v)) for v in (17, 24, 31)}
t1 = REGISTRY.snapshot().get("exec.traces", 0)
assert t1 == t0, f"warm bindings re-traced ({t1 - t0} new traces)"
s_off = Session({"tpch": conn}, properties={
    "result_cache_enabled": False, "plan_templates": False})
for v, df in res.items():
    assert df.equals(s_off.sql(tpl.format(v))), \
        f"plan_templates changed results at binding {v}"
# PREPARE / EXECUTE ... USING binds by position, same executable
s.sql("prepare t10 from select count(*) c from orders"
      " where o_orderkey < ?")
a = s.sql("execute t10 using 512")
t2 = REGISTRY.snapshot().get("exec.traces", 0)
b = s.sql("execute t10 using 4096")
assert REGISTRY.snapshot().get("exec.traces", 0) == t2, \
    "EXECUTE with a new binding re-traced"
assert a.equals(s_off.sql("select count(*) c from orders"
                          " where o_orderkey < 512"))
assert b.equals(s_off.sql("select count(*) c from orders"
                          " where o_orderkey < 4096"))
hits = REGISTRY.snapshot().get("prepare.template_hit", 0)
assert hits >= 4, f"template hits not counted ({hits})"
assert global_pool().reserved_bytes == 0, "global pool reservation leak"
print("template smoke: 3 bindings + 2 EXECUTEs re-traced 0 steps, "
      "on/off identical, pool balance 0")
PY

timeout -k 10 420 env JAX_ENABLE_X64=1 python - <<'PY' || exit $?
# Flight-recorder smoke (ISSUE-12 acceptance): exchange-skew telemetry
# on a zipfian repartition, auto-captured fault post-mortems with JSON
# round-trip, and the compile-cost ledger's measured amortization.
import json
import re
import sys

import numpy as np
import pandas as pd

sys.path.insert(0, ".")
from __graft_entry__ import _provision_virtual_mesh

_provision_virtual_mesh(8)

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.parallel.mesh import make_mesh
from presto_tpu.runtime import faults
from presto_tpu.runtime.memory import global_pool
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

conn = TpchConnector(sf=0.005)
rng = np.random.default_rng(12)

# 1) zipfian repartition: one hot key owns ~85% of the probe rows ->
#    the partition it hashes to receives most of the exchange; the
#    balanced stream spreads 64 keys uniformly
s = Session({"tpch": conn}, mesh=make_mesh(8), properties={
    "result_cache_enabled": False, "broadcast_join_row_limit": 0})
mem = s.catalog.connector("memory")
hot = np.where(rng.random(4096) < 0.85, 7, rng.integers(0, 64, 4096))
mem.create_table("zipf", pd.DataFrame({"k": hot.astype(np.int64)}))
mem.create_table("flat", pd.DataFrame(
    {"k": (np.arange(4096) % 64).astype(np.int64)}))
mem.create_table("dim", pd.DataFrame(
    {"dk": np.arange(64, dtype=np.int64)}))
q = "select count(*) c from {} join dim on k = dk"
before = REGISTRY.snapshot().get("exchange.skew.count", 0)
out_skew = s.explain_analyze(q.format("zipf"))
out_flat = s.explain_analyze(q.format("flat"))
assert REGISTRY.snapshot().get("exchange.skew.count", 0) > before, \
    "exchange.skew histogram not populated"

def join_skew(rendered):
    m = re.search(r"Join .*skew ([\d.]+)x", rendered)
    assert m, "no skew rendered on the Join:\n" + rendered
    return float(m.group(1))

ratio_hot, ratio_flat = join_skew(out_skew), join_skew(out_flat)
assert ratio_hot > 2.0, f"zipfian skew ratio {ratio_hot} not > 2x"
assert ratio_flat < 2.0, f"balanced skew ratio {ratio_flat} not ~1x"
ps = s.sql("select node_type from plan_stats where skew > 2")
assert len(ps) >= 1, "skew not persisted into system.plan_stats"

# 2) injected fault -> auto-captured post-mortem, JSON round trip
inj = faults.FaultInjector()
inj.inject("aggregation", times=None)
failed = False
try:
    with faults.injected(inj):
        s.sql(q.format("zipf"))
except Exception:
    failed = True
assert failed, "injected fault did not surface"
rec = s.flight.latest()
assert rec is not None and rec.state == "FAILED", "no post-mortem captured"
d = json.loads(s.export_flight_record(query_id=rec.query_id))
assert d["errorCode"] and d["planRender"] and d["spans"] and d["metrics"], d
assert d["pool"]["reserved_bytes"] == 0, "post-mortem holds pool capacity"

# 3) compile-cost ledger: warm template re-run -> hits + measured
#    amortization in system.exec_cache
s2 = Session({"tpch": conn}, properties={"result_cache_enabled": False})
tq = ("select count(*) c from orders where o_orderkey < {}")
s2.sql(tq.format(1000))
s2.sql(tq.format(5000))  # warm: same template, new binding
ec = s2.sql("select sum(hits) h, sum(compile_s_saved) saved "
            "from exec_cache")
assert float(ec["h"][0]) > 0, "warm re-run produced no exec-cache hits"
assert float(ec["saved"][0]) > 0, "compile_s_saved not measured"

assert global_pool().reserved_bytes == 0, "global pool reservation leak"
print("flight smoke: zipf skew %.1fx / balanced %.1fx, post-mortem "
      "JSON ok (%d spans), ledger saved %.3fs over %d hits, pool 0"
      % (ratio_hot, ratio_flat, len(d["spans"]),
         float(ec["saved"][0]), int(ec["h"][0])))
PY

timeout -k 10 300 env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python - <<'PY' || exit $?
# Serving smoke (ISSUE-14 acceptance): two tenants through the
# fairness scheduler, over-quota bounded, batched dispatch fires with
# results bit-identical to serial, /metrics parses, pool drains.
import re
import sys
import threading

sys.path.insert(0, ".")
import pandas as pd

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.memory import global_pool
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session
from presto_tpu.server.frontend import QueryServer
from presto_tpu.server.scheduler import TenantSpec

conn = TpchConnector(sf=0.005)
# aggressor cap 4 with 5 clients: the 5th parks at the scheduler
# (over-quota preemption, asserted below) while the admitted four meet
# at the batch gate — a cap below the client count at the GATE side
# would starve batch formation, the quota must bite at the SCHEDULER
qs = QueryServer({"tpch": conn},
                 tenants=[TenantSpec("aggressor", weight=1.0,
                                     max_concurrent=4),
                          TenantSpec("interactive", weight=4.0)],
                 properties={"result_cache_enabled": False})
fmt = ("select l_orderkey, l_linenumber, l_quantity from lineitem"
       " where l_extendedprice < {}"
       " order by l_orderkey, l_linenumber limit 25")
inter_q = ("select l_returnflag, count(*) c from lineitem"
           " group by l_returnflag order by l_returnflag")
qs.execute(fmt.format(1000), tenant="aggressor")  # warm the template
qs.execute(inter_q, tenant="interactive")
d0 = REGISTRY.snapshot().get("batch.dispatched", 0)
results, errors = {}, []

def agg_worker(v):
    try:
        results[v] = qs.execute(fmt.format(v), tenant="aggressor",
                                timeout_s=120)
    except Exception as e:  # noqa: BLE001
        errors.append(f"aggressor {v}: {e}")

def inter_worker(i):
    try:
        results[f"i{i}"] = qs.execute(inter_q, tenant="interactive",
                                      timeout_s=120)
    except Exception as e:  # noqa: BLE001
        errors.append(f"interactive {i}: {e}")

# deterministic batch formation (the test-suite hold): the FIRST query
# through run_plan blocks until followers have queued at the batch
# gate, so the next leader provably drains a multi-binding batch —
# no scheduler/GIL timing race decides whether the gate fuses
from presto_tpu.runtime.lifecycle import QueryManager

gate = qs.session.query_manager.batch_gate
release = threading.Event()
first = threading.Event()
orig_run_plan = QueryManager.run_plan

def gated(self, executor, plan, info, recorder):
    if not first.is_set():
        first.set()
        release.wait(60)
    return orig_run_plan(self, executor, plan, info, recorder)

QueryManager.run_plan = gated
lits = [3000, 22000, 47000, 72000, 91000]
threads = [threading.Thread(target=agg_worker, args=(v,)) for v in lits]
threads.append(threading.Thread(target=inter_worker, args=(0,)))
threads[0].start()
assert first.wait(60), "first aggressor never reached run_plan"
for t in threads[1:]:
    t.start()
import time as _time
deadline = _time.monotonic() + 60
while _time.monotonic() < deadline:
    if sum(gate.queue_depth(fp) for fp in list(gate._templates)) >= 2:
        break
    _time.sleep(0.01)
release.set()
for t in threads:
    t.join(120)
QueryManager.run_plan = orig_run_plan
assert not errors, errors
fused = REGISTRY.snapshot().get("batch.dispatched", 0) - d0
assert fused >= 1, "batched dispatch did not fire"
# a second unheld burst exercises the scheduler+gate interplay live
threads = [threading.Thread(target=agg_worker, args=(v + 100,))
           for v in lits] + \
          [threading.Thread(target=inter_worker, args=(1,))]
for t in threads:
    t.start()
for t in threads:
    t.join(120)
assert not errors, errors

# batched results identical to serial execution (templates off)
off = Session({"tpch": conn}, properties={
    "result_cache_enabled": False, "plan_templates": False})
checked = 0
for v, df in results.items():
    if isinstance(v, int) and checked < 6:
        assert df.equals(off.sql(fmt.format(v))), \
            f"batched result differs at binding {v}"
        checked += 1

# over-quota tenant bounded at its concurrency cap (the 5th client
# was preempted at admission while at the cap)
snap = {r["tenant"]: r for r in qs.scheduler.snapshot()}
assert snap["aggressor"]["peak_running"] <= 4, snap["aggressor"]
assert snap["aggressor"]["over_quota_blocked"] >= 1, snap["aggressor"]
assert snap["interactive"]["admitted"] >= 1

# tenant attribution visible in system.query_history
hist = qs.session.sql("select tenant from query_history"
                      " where tenant <> ''")
assert {"aggressor", "interactive"} <= set(hist["tenant"].tolist())

# /metrics scrape parses line-by-line (the gate-7 grammar)
text = qs.metrics_text()
lines = text.splitlines()
assert lines[-1] == "# EOF"
sample = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*(\{quantile="0\.\d+"\})? '
                    r'-?\d+(\.\d+)?(e-?\d+)?$')
for line in lines[:-1]:
    if line.startswith("# TYPE ") or line.startswith("# HELP "):
        continue
    assert sample.match(line), f"unparseable exposition line: {line!r}"
assert "presto_tpu_batch_dispatched_total" in text
assert "presto_tpu_tenant_admitted_total" in text

summary = qs.shutdown(drain_timeout_s=15)
assert summary["drained"] and summary["pool_reserved_bytes"] == 0
assert global_pool().reserved_bytes == 0, "global pool reservation leak"
served = int(REGISTRY.snapshot().get("batch.served", 0))
print("serving smoke: %d batch dispatches (%d served), aggressor peak "
      "%d <= cap 4 (%d over-quota blocks), %d bindings verified "
      "identical, metrics parse ok, pool 0"
      % (int(fused), served, snap["aggressor"]["peak_running"],
         int(snap["aggressor"]["over_quota_blocked"]), checked))
PY

timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'PY' || exit $?
# Gate 12: the planned hybrid-spill tier — larger-than-budget joins
# execute out-of-core WITHOUT the OOM ladder's failed-attempt
# round-trip, bit-identical to the resident run.
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.memory import global_host_spill_budget
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

Q3ISH = (
    "select o_orderkey, sum(l_extendedprice * (1 - l_discount)) as rev "
    "from orders, lineitem where o_orderkey = l_orderkey "
    "and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15' "
    "group by o_orderkey order by rev desc, o_orderkey limit 20"
)
conn = TpchConnector(sf=0.005, units_per_split=1 << 12)
want = Session({"tpch": conn}).sql(Q3ISH)

# the filtered orders build estimates ~17.5 KB at SF 0.005: a 4400-byte
# budget puts it ~4x over, squarely in hybrid territory
before = REGISTRY.snapshot()
s = Session({"tpch": conn}, properties={"join_build_budget_bytes": 4400})
plan = s.explain(Q3ISH)
assert "spill=hybrid(" in plan, f"EXPLAIN missing spill decision:\n{plan}"
got = s.sql(Q3ISH)
assert got.equals(want), "hybrid-spill rows differ from resident run"
snap = REGISTRY.snapshot()


def delta(name):
    return snap.get(name, 0) - before.get(name, 0)


assert delta("spill.planned_hybrid") >= 1, "planned hybrid never executed"
assert delta("query.oom_degraded") == 0, "planned spill paid a ladder rung"
assert delta("query.backend_oom") == 0, "planned spill hit a backend OOM"
assert delta("spill.partitions_streamed") >= 1, "no partition streamed"
assert s.pool().reserved_bytes == 0, "memory pool reservation leak"
assert global_host_spill_budget().reserved_bytes == 0, \
    "host-spill budget reservation leak"
hist = [e for e in s.query_history[-1].rung_history
        if e.get("kind") == "planned_hybrid"]
assert hist, "no planned_hybrid entry in rung history"
print("spill smoke: %d hybrid decisions, %d partitions streamed, "
      "%d transfer bytes, 0 ladder rungs, rows identical, pool 0"
      % (int(delta("spill.planned_hybrid")),
         int(delta("spill.partitions_streamed")),
         int(delta("spill.transfer_bytes"))))
PY

timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'PY' || exit $?
# Gate 13: streaming ingestion + continuous queries — micro-batch
# appends bump the table epoch, subscriptions re-fire with fresh rows
# carrying their fire-time epochs, a synchronized same-template
# refresh burst fuses at the batch gate (deterministic hold, the gate
# 11 idiom), and warm refreshes re-trace ZERO jitted steps: the epoch
# bump invalidates RESULTS, never executables.
import threading
import time as _time

import numpy as np
import pandas as pd

from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runtime.lifecycle import QueryManager
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session
from presto_tpu.server.frontend import QueryServer
from presto_tpu.stream import StreamWriter

conn = MemoryConnector()
s = Session({"memory": conn}, properties={"batched_dispatch": True,
                                          "result_cache_enabled": True})
server = QueryServer(session=s)
w = StreamWriter(s)


def ticks(n, lo=0):
    k = np.arange(lo, lo + n, dtype=np.int64)
    return pd.DataFrame({"k": k, "v": (k * 3) % 100})


r0 = w.append("ticks", ticks(50_000))
assert r0.created and r0.epoch == 1, r0
# every literal sits above the value range (v in 0..99), so each
# refresh returns ALL rows: row count vs the append ledger is a direct
# zero-stale oracle
fmt = "select k, v from ticks where v < {} order by k limit 1000000"
subs = [server.subscribe(fmt.format(lit), f"dash-{i}")
        for i, lit in enumerate((150, 175, 200, 225))]
for sub in subs:
    res = sub.wait_for_seq(1, timeout_s=120)
    assert len(res.df) == 50_000 and res.epochs["ticks"] == 1

# deterministic fuse: hold the FIRST refresh inside run_plan until the
# other dashboards queue at the gate, then the next leader provably
# drains a multi-binding batch
gate = s.query_manager.batch_gate
release, first = threading.Event(), threading.Event()
orig_run_plan = QueryManager.run_plan


def gated(self, executor, plan, info, recorder):
    if not first.is_set():
        first.set()
        release.wait(60)
    return orig_run_plan(self, executor, plan, info, recorder)


t0 = REGISTRY.snapshot().get("exec.traces", 0)
d0 = REGISTRY.snapshot().get("batch.dispatched", 0)
QueryManager.run_plan = gated
try:
    r1 = w.append("ticks", ticks(4000, lo=1_000_000))
    assert first.wait(60), "no refresh reached run_plan after the append"
    deadline = _time.monotonic() + 60
    while _time.monotonic() < deadline:
        if sum(gate.queue_depth(fp) for fp in list(gate._templates)) >= 2:
            break
        _time.sleep(0.01)
    release.set()
    got = [sub.wait_for_epoch("ticks", r1.epoch, timeout_s=120)
           for sub in subs]
finally:
    QueryManager.run_plan = orig_run_plan
snap = REGISTRY.snapshot()
for res in got:
    assert len(res.df) == 54_000, "STALE refresh after append"
    assert res.epochs["ticks"] >= r1.epoch
fused = snap.get("batch.dispatched", 0) - d0
assert fused >= 1, "synchronized refresh burst never fused at the gate"
assert snap.get("exec.traces", 0) == t0, "warm refresh re-traced"
assert snap.get("stream.appends", 0) >= 2, "stream.appends not counted"
assert snap.get("subscription.fired", 0) >= 8, "subscription.fired low"
summary = server.shutdown(drain_timeout_s=15)
assert summary["drained"] and summary["pool_reserved_bytes"] == 0
print("streaming smoke: %d appends -> epoch %d, %d refreshes "
      "(%d fused dispatches), fresh rows 54000/54000, 0 warm re-traces, "
      "pool 0"
      % (int(snap.get("stream.appends", 0)), int(r1.epoch),
         int(snap.get("subscription.fired", 0)), int(fused)))
PY

timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'PY' || exit $?
# Gate 14: serving-tier health observability — end-to-end trace
# propagation over HTTP (client traceparent honored and echoed, linked
# spans from frontend submit through the batch gate to device steps
# and poll), device telemetry queryable, the armed watchdog silent on
# a quiet baseline, and a seeded latency regression tripping EXACTLY
# ONE health_breach with a complete flight-record post-mortem.
import json
import threading
import time as _time
import urllib.request

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.health import HealthMonitor
from presto_tpu.runtime.lifecycle import QueryManager
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.server.frontend import HttpFrontend, QueryServer
from presto_tpu.server.scheduler import TenantSpec

server = QueryServer({"tpch": TpchConnector(sf=0.005)},
                     tenants=[TenantSpec("web", weight=2.0,
                                         slo_latency_s=60.0)],
                     properties={"result_cache_enabled": False})
s = server.session
assert server.health is not None and server.health.running()
http = HttpFrontend(server, port=0).start_background()
base = "http://127.0.0.1:%d" % http.port

# ---- trace propagation: client traceparent honored end to end -------
TID = "4bf92f3577b34da6a3ce929d0e0e4736"
req = urllib.request.Request(
    base + "/v1/statement",
    data=(b"select l_orderkey, l_linenumber, l_quantity from lineitem"
          b" where l_extendedprice < 1500.0"
          b" order by l_orderkey, l_linenumber limit 10"),
    headers={"X-Presto-Tenant": "web",
             "traceparent": "00-%s-00f067aa0ba902b7-01" % TID},
    method="POST")
resp = urllib.request.urlopen(req, timeout=60)
sub = json.loads(resp.read())
tp_out = resp.headers.get("traceparent", "")
assert tp_out.split("-")[1] == TID, "201 did not echo the client trace-id"
assert resp.headers.get("X-Presto-Trace") == TID
page = {}
deadline = _time.monotonic() + 120
while _time.monotonic() < deadline:
    presp = urllib.request.urlopen(base + sub["nextUri"], timeout=60)
    page = json.loads(presp.read())
    if page["state"] in ("FINISHED", "FAILED"):
        break
    _time.sleep(0.05)
assert page["state"] == "FINISHED", page
assert presp.headers.get("traceparent", "").split("-")[1] == TID

# the exported trace links the whole serving path under the client id
engine_qid = server._queries[sub["id"]]["trace"]["query_id"]
tracer = s.traces.for_query(engine_qid)
assert tracer is not None and tracer.trace_token == TID
names = [sp.name for sp in tracer.spans]
for needed in ("frontend:submit", "batch:gate_wait", "admission",
               "frontend:poll"):
    assert needed in names, "missing linked span %r in %s" % (needed,
                                                              names)
assert any(n.startswith(("step:", "fragment:")) for n in names), names

# ---- device telemetry is queryable (CPU-safe rows) ------------------
df = s.sql("select device_id, dispatch_wall_s, dispatches "
           "from device_stats")
assert len(df) >= 1 and int(df["dispatches"][0]) >= 1

# ---- quiet baseline: the armed watchdog sampled and stayed silent ---
_time.sleep(0.6)  # a few 0.25s cadence ticks
assert server.health.snapshot(), "watchdog never sampled"
assert server.health.breaches() == [], server.health.breaches()
b0 = REGISTRY.snapshot().get("health.breach", 0)
# close the threaded sampler: the seeded regression below is driven
# deterministically through a manual monitor's sample()
server.health.close()

# ---- seeded regression: exactly one breach + full post-mortem -------
fmt = ("select l_orderkey, l_linenumber, l_quantity from lineitem"
       " where l_extendedprice < %d"
       " order by l_orderkey, l_linenumber limit 10")
server.execute(fmt % 900, tenant="web")  # warm the template
# flush cold-compile outliers out of the watchdog's 64-entry latency
# window so the baseline reflects the warm serving steady state
for i in range(64):
    server.execute(fmt % (1000 + i), tenant="web")
mon = HealthMonitor(s, min_samples=3, p99_factor=3.0, cooldown_s=1000.0)
s.health = mon  # re-point system.health at the deterministic monitor
for _ in range(4):
    assert mon.sample()["breach"] == 0, "quiet baseline breached"
fast_p99 = max(i.execution_s for i in s.history.infos()[-64:])
delay = max(0.75, 6.0 * fast_p99)

orig_ladder = QueryManager._run_with_oom_ladder


def slow_ladder(self, executor, plan, info, recorder, ctx):
    _time.sleep(delay)
    return orig_ladder(self, executor, plan, info, recorder, ctx)


QueryManager._run_with_oom_ladder = slow_ladder
errors = []
try:
    # TWO completed regressions: with a full 64-entry latency window
    # the nearest-rank p99 sits at the second-largest observation
    server.execute(fmt % 5000, tenant="web")
    server.execute(fmt % 5200, tenant="web")

    def inflight_victim():
        try:
            server.execute(fmt % 6000, tenant="web")
        except Exception as e:
            errors.append(e)

    t = threading.Thread(target=inflight_victim, daemon=True)
    t.start()
    wait_end = _time.monotonic() + 60
    while (not s.query_manager.inflight_snapshot()
           and _time.monotonic() < wait_end):
        _time.sleep(0.005)
    assert s.query_manager.inflight_snapshot(), "victim never in flight"
    cur = mon.sample()
    assert cur["breach"] == 1 and "p99" in cur["reason"], cur
    for _ in range(3):  # the latch holds the incident to ONE breach
        assert mon.sample()["breach"] == 0
    t.join(120)
finally:
    QueryManager._run_with_oom_ladder = orig_ladder
assert not errors, errors
events = mon.breaches()
assert len(events) == 1
assert REGISTRY.snapshot().get("health.breach", 0) == b0 + 1
recs = [r for r in s.flight.records() if "health_breach" in r.triggers]
assert len(recs) == 1, [r.triggers for r in s.flight.records()]
rec = recs[0]
assert rec.query_id == events[0]["query_id"]
assert rec.plan_render and rec.trace_enabled and rec.spans
hdf = s.sql("select breach, reason from health")
assert int(sum(hdf["breach"])) == 1

summary = server.shutdown(drain_timeout_s=15)
assert summary["drained"] and summary["pool_reserved_bytes"] == 0
http.shutdown()
print("health smoke: traceparent %s honored across %d linked spans, "
      "%d device rows, quiet watchdog 0 breaches, seeded regression "
      "-> 1 health_breach (%d spans in post-mortem), pool 0"
      % (TID[:8], len(names), len(df), len(rec.spans)))
PY

timeout -k 10 420 env JAX_PLATFORMS=cpu python - <<'PY' || exit $?
# Gate 15: closed-loop overload control — shed-vs-no-shed goodput
# under a deterministic 4x storm, seeded brown-out engage + recovery,
# cooperative cancel of a RUNNING query, drained budgets.
import sys
import threading
import time as _time

sys.path.insert(0, ".")

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.errors import ServerOverloaded
from presto_tpu.runtime.lifecycle import QueryManager
from presto_tpu.server.frontend import QueryServer
from presto_tpu.server.scheduler import TenantSpec

SQL = "select count(*) c from nation"
PROPS = {"health_monitor": False, "result_cache_enabled": False,
         "batched_dispatch": False}

# warm the executable cache so storm timing is sleep-dominated
warm = QueryServer({"tpch": TpchConnector(sf=0.005)}, properties=PROPS)
warm.execute(SQL)
warm.shutdown()

# ---- A/B storm: every query takes a fixed 0.2s on ONE slot ----------
orig_ladder = QueryManager._run_with_oom_ladder


def slow_ladder(self, executor, plan, info, recorder, ctx):
    _time.sleep(0.25)
    return orig_ladder(self, executor, plan, info, recorder, ctx)


def storm(shed_on):
    # one slot, every query sleeps 0.25s, a 1.0s deadline from submit:
    # the deadline can drain ~3 queries, the burst offers 8 (>=4x over
    # what completes). The shed-on server's queue ceiling admits
    # exactly the prefix that CAN meet its deadline; the shed-off
    # server queues everyone and positions past the drain rate burn a
    # worker + a deadline failure each — the admitted prefixes behave
    # identically in both runs, so goodput(on) >= goodput(off) is a
    # structural fact, not a timing race.
    srv = QueryServer(
        {"tpch": TpchConnector(sf=0.005)}, total_slots=1,
        shed_queue_limit=(3 if shed_on else None),
        properties=PROPS)
    qids, shed = [], 0
    hold = srv.scheduler.acquire("default")  # queue builds while pinned
    try:
        for _ in range(8):
            try:
                qids.append(srv.submit(SQL, deadline_s=1.0))
            except ServerOverloaded as e:
                assert e.retryable and e.retry_after_s > 0
                shed += 1
            else:
                # admitted workers enqueue asynchronously; let each
                # reach the fair queue so the ceiling sees true depth
                t0 = _time.monotonic()
                while (srv.scheduler.queue_depth() < len(qids)
                       and _time.monotonic() - t0 < 10.0):
                    _time.sleep(0.002)
        srv.scheduler.release(hold)
        hold = None
        good = 0
        for qid in qids:
            assert srv._queries[qid]["done"].wait(120), "storm hang"
            page = srv.poll(qid)
            if page["state"] == "FINISHED":
                good += 1
            else:
                assert page["errorCode"] in (
                    "EXCEEDED_TIME_LIMIT", "QUERY_CANCELLED",
                    "SERVER_OVERLOADED"), page
        pool = srv.session.pool().reserved_bytes
        assert pool == 0, pool
        return good, shed
    finally:
        if hold is not None:
            srv.scheduler.release(hold)
        srv.shutdown()


QueryManager._run_with_oom_ladder = slow_ladder
try:
    good_off, shed_off = storm(shed_on=False)
    good_on, shed_on_n = storm(shed_on=True)
finally:
    QueryManager._run_with_oom_ladder = orig_ladder
assert shed_off == 0 and shed_on_n >= 1, (shed_off, shed_on_n)
assert good_on >= good_off, (
    "shedding made goodput WORSE: on=%d off=%d" % (good_on, good_off))

# ---- seeded breach -> brown-out; recovery re-arms exact service -----
srv = QueryServer(
    {"tpch": TpchConnector(sf=0.005)},
    tenants=[TenantSpec("dash", brownout="approx"),
             TenantSpec("batch", brownout="shed")],
    properties=dict(PROPS, brownout_cooldown_s=0.5))
try:
    srv.overload.on_breach({"kind": "seeded"})
    qid = srv.submit(SQL, tenant="dash")
    assert srv._queries[qid]["done"].wait(120)
    page = srv.poll(qid)
    assert page["state"] == "FINISHED" and page.get("approximate") is True
    try:
        srv.submit(SQL, tenant="batch")
        raise AssertionError("brownout='shed' tenant was admitted")
    except ServerOverloaded:
        pass
    _time.sleep(0.6)  # breach-free cooldown elapses
    assert not srv.overload.engaged, "brown-out never recovered"
    qid = srv.submit(SQL, tenant="dash")
    assert srv._queries[qid]["done"].wait(120)
    assert "approximate" not in srv.poll(qid), "recovery did not re-arm"

    # ---- cancel of a RUNNING query frees its reservations -----------
    entered = threading.Event()

    def held_ladder(self, executor, plan, info, recorder, ctx):
        entered.set()
        _time.sleep(0.25)
        return orig_ladder(self, executor, plan, info, recorder, ctx)

    QueryManager._run_with_oom_ladder = held_ladder
    try:
        qid = srv.submit(
            "select n_name, count(*) c, sum(s_acctbal) b from supplier "
            "join nation on s_nationkey = n_nationkey group by n_name "
            "order by n_name")
        assert entered.wait(120), "query never started"
        out = srv.cancel(qid, reason="gate 15")
        assert out["cancelled"] is True
        assert srv._queries[qid]["done"].wait(120)
        page = srv.poll(qid)
        assert page["state"] == "FAILED" and (
            page["errorCode"] == "QUERY_CANCELLED"), page
    finally:
        QueryManager._run_with_oom_ladder = orig_ladder
    pool = srv.session.pool().reserved_bytes
    assert pool == 0, "cancelled query leaked %d bytes" % pool
finally:
    summary = srv.shutdown()
assert summary["drained"] and summary["pool_reserved_bytes"] == 0
print("overload smoke: storm goodput on=%d/off=%d (%d shed, typed), "
      "brown-out engaged -> approx flagged + shed tenant refused -> "
      "recovered, RUNNING cancel typed QUERY_CANCELLED, pool 0"
      % (good_on, good_off, shed_on_n))
PY

timeout -k 10 420 env JAX_ENABLE_X64=1 python - <<'PY' || exit $?
# Gate 16: adaptivity smoke (ISSUE-20 acceptance) — a recurring
# zipf-skewed repartition join is rewritten with skew salting from
# plan-stats history (bit-identical rows, EXPLAIN renders the salted
# exchange, the measured skew rebalances under 2x, the decision lands
# in system.adaptive), and the serving warmer keeps a warm serving
# window free of cold compiles.
import re
import sys
import time

import numpy as np
import pandas as pd

sys.path.insert(0, ".")
from __graft_entry__ import _provision_virtual_mesh

_provision_virtual_mesh(8)

from presto_tpu.cache.exec_cache import trace_delta
from presto_tpu.parallel.mesh import make_mesh
from presto_tpu.runtime.memory import global_pool
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session
from presto_tpu.server.frontend import QueryServer

rng = np.random.default_rng(20)
rows = 4096
keys = np.where(rng.random(rows) < 0.85, 7, rng.integers(0, 64, rows))
skewed = pd.DataFrame({"k": keys.astype(np.int64),
                       "v": rng.integers(0, 100, rows)})
dim = pd.DataFrame({"dk": np.arange(64, dtype=np.int64),
                    "dv": np.arange(64, dtype=np.int64)})
q = ("select k, dv, count(*) c, sum(v) sv from skewed "
     "join dim on k = dk group by k, dv order by k, dv")


def mk(adaptive):
    s = Session({}, mesh=make_mesh(8), properties={
        "result_cache_enabled": False,
        "broadcast_join_row_limit": 0,  # force the repartition join
        "adaptive_execution": adaptive,
    })
    mem = s.catalog.connector("memory")
    mem.create_table("skewed", skewed)
    mem.create_table("dim", dim)
    return s


want, _ = mk(False).execute(q)

before = REGISTRY.snapshot().get("adaptive.salted", 0)
s = mk(True)
for i in range(4):
    got, _ = s.execute(q)
    assert got.equals(want), f"adaptive run {i} diverged from baseline"
salted = REGISTRY.snapshot().get("adaptive.salted", 0) - before
assert salted >= 1, "recurring zipfian join never salted"
rendered = s.explain(q)
assert "repartition=salted(" in rendered, rendered
ana = s.explain_analyze(q)
m = re.search(r"Join .*skew ([\d.]+)x", ana)
assert m, "no skew rendered on the Join:\n" + ana
skew = float(m.group(1))
assert skew < 2.0, f"post-adaptation skew {skew}x not rebalanced"
logged = s.sql("select kind, applied from adaptive "
               "where kind = 'salt' and applied = 1")
assert len(logged) >= 1, "salt decision missing from system.adaptive"

# serving warmer: recurring template warms in the background, then a
# warm window of serving traffic must trace NOTHING new
server = QueryServer(session=s, warm_top_k=2, warm_interval_s=0.1)
try:
    server.execute(q)
    server.execute(q)
    deadline = time.monotonic() + 15.0
    while not server._warmed and time.monotonic() < deadline:
        time.sleep(0.1)
    assert server._warmed, "warmer never warmed the recurring template"
    with trace_delta() as td:
        for _ in range(3):
            server.execute(q)
    assert td.traces == 0, \
        f"{td.traces} cold compile(s) in the warm serving window"
finally:
    server.shutdown(drain_timeout_s=10.0)
assert global_pool().reserved_bytes == 0, "global pool reservation leak"
print("adaptivity smoke: salted %d run(s), EXPLAIN salted, post-adapt "
      "skew %.1fx, warm serving 0 cold compiles, pool 0"
      % (salted, skew))
PY

timeout -k 10 180 env JAX_PLATFORMS=cpu bash scripts/lint.sh || exit $?

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
