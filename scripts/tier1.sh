#!/usr/bin/env bash
# Tier-1 verify — the checked-in form of the ROADMAP.md command.
#
# Three gates, cheapest first:
#   1. `python -m compileall` over the package: a syntax/static gate
#      that fails in seconds instead of letting a typo ride to the
#      middle of the pytest run.
#   2. Cache cold-vs-warm smoke: one TPC-H aggregation twice in one
#      session, then once more in a fresh session — the warm runs must
#      hit the result cache and the executable cache with ZERO
#      re-traces and identical rows (ISSUE-2 acceptance).
#   3. The tier-1 pytest suite on the CPU backend (virtual-device
#      distributed tests included; `slow` marks excluded), with the
#      same flags and timeout the driver uses.
#
# Exit status is the pytest status (or the compileall status when the
# static gate fails); DOTS_PASSED echoes the passed-test count the
# driver greps for.
set -o pipefail
cd "$(dirname "$0")/.."

python -m compileall -q presto_tpu || exit $?

timeout -k 10 240 env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python - <<'PY' || exit $?
import sys

sys.path.insert(0, ".")
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

conn = TpchConnector(sf=0.005)
q = ("select l_returnflag, l_linestatus, count(*) c, sum(l_quantity) q "
     "from lineitem group by l_returnflag, l_linestatus "
     "order by l_returnflag, l_linestatus")
s = Session({"tpch": conn})
a = s.sql(q)
t0 = REGISTRY.snapshot().get("exec.traces", 0)
b = s.sql(q)
snap = REGISTRY.snapshot()
assert snap.get("exec.traces", 0) == t0, "warm run re-traced"
assert snap.get("result_cache.hit", 0) >= 1, "no result-cache hit"
s2 = Session({"tpch": conn}, properties={"result_cache_enabled": False})
c = s2.sql(q)
snap2 = REGISTRY.snapshot()
assert snap2.get("exec_cache.hit", 0) >= 1, "no executable-cache hit"
assert snap2.get("exec.traces", 0) == t0, "cross-session run re-traced"
assert a.equals(b) and a.equals(c), "cached results differ"
print("cache smoke: exec_cache.hit=%d result_cache.hit=%d traces=%d"
      % (snap2.get("exec_cache.hit", 0), snap2.get("result_cache.hit", 0),
         snap2.get("exec.traces", 0)))
PY

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
