#!/usr/bin/env bash
# Tier-1 verify — the checked-in form of the ROADMAP.md command.
#
# Two gates, cheapest first:
#   1. `python -m compileall` over the package: a syntax/static gate
#      that fails in seconds instead of letting a typo ride to the
#      middle of the pytest run.
#   2. The tier-1 pytest suite on the CPU backend (virtual-device
#      distributed tests included; `slow` marks excluded), with the
#      same flags and timeout the driver uses.
#
# Exit status is the pytest status (or the compileall status when the
# static gate fails); DOTS_PASSED echoes the passed-test count the
# driver greps for.
set -o pipefail
cd "$(dirname "$0")/.."

python -m compileall -q presto_tpu || exit $?

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
