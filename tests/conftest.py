"""Test harness config.

Per SURVEY §4: tests run on a *virtual multi-device CPU mesh* so the real
`all_to_all` / `all_gather` collective paths execute without TPU hardware
(the analog of the reference's in-process DistributedQueryRunner, which
boots coordinator+workers in one JVM with real HTTP exchanges).

Env vars must be set before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from __graft_entry__ import _provision_virtual_mesh  # noqa: E402

_provision_virtual_mesh(8)

import jax  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(7)


@pytest.fixture(autouse=True)
def _memory_pool_leak_check():
    """Pool-accounting invariant, enforced suite-wide: every query
    reaching a terminal state must have released its memory-pool
    reservation (runtime/lifecycle.py releases in the run_plan
    ``finally``). A leak here means some failure path skipped release —
    the bug class the chaos suite exists to catch."""
    yield
    from presto_tpu.runtime.memory import pool_leaks

    leaks = pool_leaks()
    assert not leaks, f"memory pool reservation leak: {leaks}"
