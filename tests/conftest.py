"""Test harness config.

Per SURVEY §4: tests run on a *virtual multi-device CPU mesh* so the real
`all_to_all` / `all_gather` collective paths execute without TPU hardware
(the analog of the reference's in-process DistributedQueryRunner, which
boots coordinator+workers in one JVM with real HTTP exchanges).

Env vars must be set before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# A site hook may pre-register an accelerator plugin and force
# jax_platforms via config (overriding the env var), which then blocks
# on hardware init. Pin the config value itself before any backend
# initializes: tests always run on the virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(7)
