"""Test harness config.

Per SURVEY §4: tests run on a *virtual multi-device CPU mesh* so the real
`all_to_all` / `all_gather` collective paths execute without TPU hardware
(the analog of the reference's in-process DistributedQueryRunner, which
boots coordinator+workers in one JVM with real HTTP exchanges).

Env vars must be set before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from __graft_entry__ import _provision_virtual_mesh  # noqa: E402

_provision_virtual_mesh(8)

import jax  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(7)


@pytest.fixture(autouse=True)
def _memory_pool_leak_check():
    """Pool-accounting invariant, enforced suite-wide: every query
    reaching a terminal state must have released its memory-pool
    reservation (runtime/lifecycle.py releases in the run_plan
    ``finally``). A leak here means some failure path skipped release —
    the bug class the chaos suite exists to catch."""
    yield
    from presto_tpu.runtime.memory import pool_leaks

    leaks = pool_leaks()
    assert not leaks, f"memory pool reservation leak: {leaks}"


@pytest.fixture(autouse=True)
def _global_state_guard(request):
    """Process-global state invariant, enforced suite-wide (the static
    twin is lint family PT4xx): a test must leave the ``PRESTO_TPU_*``
    env switches, the exec-cache bound/population, and the metrics
    registry exactly as it found them — sessions mirror properties into
    the env and caches are process-wide, so an unrestored mutation
    silently re-routes every later test (the recurring CHANGES.md
    gotcha this guard retires). Unrestorable wipes (REGISTRY.reset)
    must be declared with ``@pytest.mark.resets_global_state``.

    On a leak the guard restores what it can (env, cache bound) before
    failing, so one offender does not cascade."""
    from presto_tpu.cache.exec_cache import EXEC_CACHE
    from presto_tpu.runtime.metrics import REGISTRY

    env_before = {k: v for k, v in os.environ.items()
                  if k.startswith("PRESTO_TPU_")}
    max_before = EXEC_CACHE.max_entries
    entries_before = len(EXEC_CACHE)
    # identity sentinel: REGISTRY.reset() drops the stat object, so a
    # fresh fetch after the test returning a DIFFERENT object proves a
    # reset happened even if something re-created the name since
    sentinel = REGISTRY.counter("conftest.guard_sentinel")
    yield
    declared = request.node.get_closest_marker(
        "resets_global_state") is not None
    leaks = []
    env_after = {k: v for k, v in os.environ.items()
                 if k.startswith("PRESTO_TPU_")}
    if env_after != env_before:
        leaks.append(f"PRESTO_TPU_* env leaked: "
                     f"{env_before!r} -> {env_after!r}")
        for k in set(env_before) | set(env_after):
            if k in env_before:
                os.environ[k] = env_before[k]
            else:
                os.environ.pop(k, None)
    if EXEC_CACHE.max_entries != max_before:
        leaks.append(f"exec_cache_max_entries leaked: "
                     f"{max_before} -> {EXEC_CACHE.max_entries}")
        EXEC_CACHE.set_max_entries(max_before)
    if len(EXEC_CACHE) < entries_before:
        # growth and at-bound eviction are normal; a shrink means an
        # undeclared EXEC_CACHE.clear()/bound drop
        leaks.append(f"exec-cache entries shrank: "
                     f"{entries_before} -> {len(EXEC_CACHE)}")
    if REGISTRY.counter("conftest.guard_sentinel") is not sentinel:
        leaks.append("metrics REGISTRY was reset")
    if leaks and not declared:
        raise AssertionError(
            "process-global state leak (declare deliberate wipes with "
            "@pytest.mark.resets_global_state): " + "; ".join(leaks))


@pytest.fixture(autouse=True)
def _health_watchdog_leak_check():
    """Watchdog-thread invariant, enforced suite-wide: every
    HealthMonitor started during a test must be closed before the test
    ends (QueryServer.shutdown closes its own; a hand-built monitor
    owns its close()). A leaked sampler thread keeps firing against
    torn-down sessions and bleeds metrics into later tests. Leaked
    monitors are closed here before failing, so one offender does not
    cascade."""
    yield
    from presto_tpu.runtime.health import live_monitors

    leaked = live_monitors()
    for mon in leaked:
        mon.close()
    assert not leaked, (
        f"{len(leaked)} health watchdog thread(s) leaked — close the "
        "HealthMonitor (or shut down its QueryServer) before the test "
        "ends")
