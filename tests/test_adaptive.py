"""Adaptive execution (plan/adaptive.py, ISSUE 20): skew-salted
repartitioning, history-driven strategy corrections, and
compile-budget-aware re-specialization.

The contract under test:

- salting is a pure repartitioning rewrite: a zipfian, a uniform, and
  a NULL-keyed join return BIT-IDENTICAL frames with adaptivity on vs
  off, while the zipfian one actually salts (``adaptive.salted``);
- decisions fire only on recurring fingerprints (runs >= 2 — the
  plan-hints corridor) and NEVER while a fault injector or the
  success recorder (``flight_record_successes``) is active
  (``adaptive.stand_down``);
- a re-specialization whose predicted compile cost (exec-cache
  ledger) exceeds its predicted win is refused and counted
  (``adaptive.compile_budget_refused``), and the refusal is sticky;
- applied decisions land in ``system.adaptive``, in flight-recorder
  post-mortems of failed adaptive runs, and the memory pool drains;
- plan-stats history round-trips through
  ``Session.export_plan_stats`` / ``import_plan_stats`` with table-
  epoch version checking (``plan_stats.import_stale``).
"""

import json
import time

import numpy as np
import pandas as pd
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.plan import nodes as N
from presto_tpu.plan.adaptive import (
    AdaptiveController,
    predicted_compile_cost,
    salt_factor,
)
from presto_tpu.runtime import faults
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=0.005)


def make_session(conn, **props):
    props.setdefault("result_cache_enabled", False)
    return Session({"tpch": conn}, properties=props)


def _counter(name: str) -> float:
    return REGISTRY.snapshot().get(name, 0)


def _find(plan, node_type):
    """First plan node of one class, pre-order."""
    if isinstance(plan, node_type):
        return plan
    for c in plan.children:
        hit = _find(c, node_type)
        if hit is not None:
            return hit
    return None


def _salt_hints(join, *, skew=7.0, hot=3, wall=5.0, runs=4):
    """Synthetic plan-hints record that makes ``join`` a salt
    candidate (the Session._plan_hints output shape)."""
    return {id(join): {
        "node_id": 5, "node_type": "Join", "skew": skew,
        "hot_partition": hot, "wall_s": wall, "runs": runs,
        "route_fallback": False, "misest": 1.0, "actual_rows": 100,
        "est_rows": 100,
    }}


# ---------------------------------------------------------------------------
# controller unit surface
# ---------------------------------------------------------------------------


def test_salt_factor_clamps():
    # next power of two >= skew, clamped into [2, min(workers, max)]
    assert salt_factor(2.0, 8, 8) == 2
    assert salt_factor(3.0, 8, 8) == 4
    assert salt_factor(6.8, 8, 8) == 8
    assert salt_factor(100.0, 8, 8) == 8   # worker clamp
    assert salt_factor(100.0, 16, 4) == 4  # salt_max clamp
    assert salt_factor(0.5, 8, 8) == 2     # floor


def test_decide_salts_recurring_skewed_join(conn):
    s = make_session(conn)
    plan = s.plan("select n_name, count(*) c from supplier "
                  "join nation on s_nationkey = n_nationkey "
                  "group by n_name")
    join = _find(plan, N.Join)
    ctl = AdaptiveController()
    decs = ctl.decide(plan, _salt_hints(join), s.catalog,
                      fingerprint="fp-unit", nworkers=8)
    by_kind = decs.get(id(join), {})
    assert "salt" in by_kind, decs
    assert by_kind["salt"].salt == 8 and by_kind["salt"].hot_partition == 3
    # single-worker sessions never salt (nothing to rebalance)
    assert AdaptiveController().decide(
        plan, _salt_hints(join), s.catalog,
        fingerprint="fp-unit", nworkers=1) == {}


def test_decide_stands_down_under_fault_injector(conn):
    s = make_session(conn)
    plan = s.plan("select count(*) c from supplier "
                  "join nation on s_nationkey = n_nationkey")
    join = _find(plan, N.Join)
    hints = _salt_hints(join)
    ctl = AdaptiveController()
    before = _counter("adaptive.stand_down")
    with faults.injected(faults.FaultInjector(seed=1)):
        assert ctl.decide(plan, hints, s.catalog,
                          fingerprint="fp-faults", nworkers=8) == {}
    # the success recorder (flight_record_successes) stands down too:
    # a repro capture must observe the baseline plan
    assert ctl.decide(plan, hints, s.catalog, fingerprint="fp-rec",
                      nworkers=8, recording=True) == {}
    assert _counter("adaptive.stand_down") == before + 2
    # for_render (EXPLAIN) bypasses runtime guards without logging
    # or stickiness — it shows the steady-state plan
    with faults.injected(faults.FaultInjector(seed=1)):
        rendered = ctl.decide(plan, hints, s.catalog,
                              fingerprint="fp-faults", nworkers=8,
                              for_render=True)
    assert "salt" in rendered.get(id(join), {})
    assert not ctl._sticky and not ctl.rows()


def test_compile_budget_refusal_counted_and_sticky(conn, monkeypatch):
    s = make_session(conn)
    plan = s.plan("select count(*) c from supplier "
                  "join nation on s_nationkey = n_nationkey")
    join = _find(plan, N.Join)
    # a microseconds-wall join can never buy a 100 s recompile
    hints = _salt_hints(join, wall=1e-6, runs=2)
    monkeypatch.setattr("presto_tpu.plan.adaptive.predicted_compile_cost",
                        lambda kind: 100.0)
    ctl = AdaptiveController()
    before = _counter("adaptive.compile_budget_refused")
    assert ctl.decide(plan, hints, s.catalog, fingerprint="fp-budget",
                      nworkers=8) == {}
    assert _counter("adaptive.compile_budget_refused") == before + 1
    refused = [r for r in ctl.rows() if not r["applied"]]
    assert refused and refused[0]["kind"] == "salt"
    assert "cost" in refused[0]["trigger"] or "cost" in str(refused[0])
    # sticky refusal: the next pass neither re-prices nor re-counts
    assert ctl.decide(plan, hints, s.catalog, fingerprint="fp-budget",
                      nworkers=8) == {}
    assert _counter("adaptive.compile_budget_refused") == before + 1


def test_sticky_decision_survives_cost_spike(conn, monkeypatch):
    """An admitted decision replays from the sticky map — later ledger
    readings never flap an already-specialized plan."""
    s = make_session(conn)
    plan = s.plan("select count(*) c from supplier "
                  "join nation on s_nationkey = n_nationkey")
    join = _find(plan, N.Join)
    hints = _salt_hints(join, wall=5.0, runs=4)
    ctl = AdaptiveController()
    first = ctl.decide(plan, hints, s.catalog, fingerprint="fp-stick",
                       nworkers=8)
    assert "salt" in first.get(id(join), {})
    monkeypatch.setattr("presto_tpu.plan.adaptive.predicted_compile_cost",
                        lambda kind: 1e9)
    again = ctl.decide(plan, hints, s.catalog, fingerprint="fp-stick",
                       nworkers=8)
    assert again[id(join)]["salt"] is first[id(join)]["salt"]


def test_predicted_compile_cost_reads_ledger():
    # unknown kinds price at 0.0: the optimistic first specialization
    assert predicted_compile_cost("no_such_step_kind") == 0.0


# ---------------------------------------------------------------------------
# corridor gating through the session (runs >= 2)
# ---------------------------------------------------------------------------


def test_decisions_require_recurrence(conn):
    """One run -> no hints -> no decisions; the corridor opens at
    runs >= 2, like the agg-bypass hints it generalizes."""
    s = make_session(conn)
    q = ("select n_name, count(*) c from supplier "
         "join nation on s_nationkey = n_nationkey group by n_name")
    s.execute(q)
    plan = s.plan(q)
    assert s._plan_hints(plan) == {}
    assert s._adaptive_decisions(plan, None, {}, s.executor) == {}
    s.execute(q)
    hints = s._plan_hints(plan)
    assert hints, "recurring fingerprint produced no hints"
    assert all(r["runs"] >= 2 for r in hints.values())


def test_adaptive_execution_property_gates_decisions(conn):
    s = make_session(conn, adaptive_execution=False)
    q = ("select n_name, count(*) c from supplier "
         "join nation on s_nationkey = n_nationkey group by n_name")
    s.execute(q)
    s.execute(q)
    plan = s.plan(q)
    hints = s._plan_hints(plan)
    assert hints
    assert s._adaptive_decisions(plan, None, hints, s.executor) == {}


# ---------------------------------------------------------------------------
# plan-stats export / import (satellite 2)
# ---------------------------------------------------------------------------


def test_export_import_roundtrip(conn, tmp_path):
    s1 = make_session(conn)
    q = ("select n_name, count(*) c from supplier "
         "join nation on s_nationkey = n_nationkey group by n_name")
    s1.execute(q)
    s1.execute(q)
    path = tmp_path / "stats.json"
    text = s1.export_plan_stats(str(path))
    payload = json.loads(path.read_text())
    assert payload["format"] == 1 and payload["entries"]
    assert json.loads(text) == payload

    s2 = make_session(conn)
    before = _counter("plan_stats.imported")
    assert s2.import_plan_stats(str(path)) >= 1
    assert _counter("plan_stats.imported") > before
    # the imported history immediately opens the corridor: hints fire
    # on the FIRST run of the restarted process (runs survived)
    plan = s2.plan(q)
    hints = s2._plan_hints(plan)
    assert hints and all(r["runs"] >= 2 for r in hints.values())


def test_import_rejects_stale_table_epochs(conn, tmp_path):
    s1 = make_session(conn)
    mem = s1.catalog.connector("memory")
    mem.create_table("little", pd.DataFrame({"k": [1, 2, 3]}))
    q = "select count(*) c from little"
    s1.execute(q)
    s1.execute(q)
    path = tmp_path / "stats.json"
    s1.export_plan_stats(str(path))

    s2 = make_session(conn)
    m2 = s2.catalog.connector("memory")
    m2.create_table("little", pd.DataFrame({"k": [1, 2, 3]}))
    m2.create_table("little", pd.DataFrame({"k": [9]}))  # epoch bump
    before = _counter("plan_stats.import_stale")
    assert s2.import_plan_stats(str(path)) == 0
    assert _counter("plan_stats.import_stale") > before
    assert s2._plan_hints(s2.plan(q)) == {}


def test_import_rejects_unknown_format(conn, tmp_path):
    from presto_tpu.runtime.errors import UserError

    s = make_session(conn)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"format": 99, "entries": []}))
    with pytest.raises(UserError):
        s.import_plan_stats(str(bad))


# ---------------------------------------------------------------------------
# serving-tier template warmer (tentpole (c))
# ---------------------------------------------------------------------------


def test_query_server_warms_recurring_templates(conn):
    from presto_tpu.server.frontend import QueryServer

    server = QueryServer(
        session=make_session(conn, health_monitor=False),
        warm_top_k=2, warm_interval_s=0.05)
    try:
        before = _counter("adaptive.warmed")
        q = "select count(*) c from nation"
        server.execute(q)
        server.execute(q)
        deadline = time.monotonic() + 10.0
        while not server._warmed and time.monotonic() < deadline:
            time.sleep(0.05)
        assert q in server._warmed
        assert _counter("adaptive.warmed") > before
        # one-shot statements and DML never warm
        assert all(sql.lstrip().lower().startswith(("select", "with"))
                   for sql in server._warmed)
    finally:
        server.shutdown(drain_timeout_s=10.0)


# ---------------------------------------------------------------------------
# differential identity on the virtual mesh (slow tier)
# ---------------------------------------------------------------------------


def _zipf_keys(rows, rng):
    return np.where(rng.random(rows) < 0.85, 7,
                    rng.integers(0, 64, rows))


def _mesh_session(conn, **props):
    from presto_tpu.parallel.mesh import make_mesh

    return Session({"tpch": conn}, mesh=make_mesh(8), properties={
        "result_cache_enabled": False,
        "broadcast_join_row_limit": 0,  # force the repartition join
        **props,
    })


def _load_join_tables(s, probe):
    mem = s.catalog.connector("memory")
    mem.create_table("probe", probe)
    mem.create_table("dim", pd.DataFrame(
        {"dk": np.arange(64, dtype=np.int64),
         "dv": np.arange(64, dtype=np.int64)}))


JOIN_Q = ("select k, dv, count(*) c, sum(v) sv from probe "
          "join dim on k = dk group by k, dv order by k, dv")


@pytest.fixture
def open_budget_gate(monkeypatch):
    """Pin the compile-budget gate OPEN for behavior tests: the gate
    reads the process-global exec-cache ledger, so suites running
    earlier would otherwise swing these tests' admit/refuse outcomes
    with whatever compile costs they happened to record. The gate
    itself is unit-tested above with a controlled ledger."""
    monkeypatch.setattr(
        "presto_tpu.plan.adaptive.predicted_compile_cost",
        lambda kind: 0.0)


def _probe_frame(shape, rng, rows=4096):
    if shape == "zipf":
        keys = _zipf_keys(rows, rng).astype(np.float64)
    elif shape == "uniform":
        keys = (np.arange(rows) % 64).astype(np.float64)
    else:  # null-heavy zipf: NULL keys never match, rows still move
        keys = _zipf_keys(rows, rng).astype(np.float64)
        keys[rng.random(rows) < 0.15] = np.nan
    return pd.DataFrame({"k": keys,
                         "v": rng.integers(0, 100, rows)})


@pytest.mark.slow
@pytest.mark.parametrize("shape", ["zipf", "uniform", "nulls"])
def test_salted_join_bit_identity(conn, rng, shape, open_budget_gate):
    """The acceptance differential: adaptivity on (salting and all)
    vs off must be bit-identical on every key distribution; the
    zipfian stream actually salts."""
    probe = _probe_frame(shape, rng)
    s_off = _mesh_session(conn, adaptive_execution=False)
    _load_join_tables(s_off, probe)
    want, _ = s_off.execute(JOIN_Q)

    before = _counter("adaptive.salted")
    s_on = _mesh_session(conn)
    _load_join_tables(s_on, probe)
    for i in range(4):
        got, _ = s_on.execute(JOIN_Q)
        assert got.equals(want), f"{shape}: run {i} diverged"
    salted = _counter("adaptive.salted") - before
    if shape == "zipf":
        assert salted >= 1, "zipfian stream never salted"
        assert "repartition=salted(" in s_on.explain(JOIN_Q)
        rows = s_on.sql("select kind, applied from adaptive "
                        "where kind = 'salt'")
        assert len(rows) >= 1 and rows["applied"].max() == 1
    if shape == "uniform":
        assert "repartition=salted(" not in s_on.explain(JOIN_Q)


@pytest.mark.slow
def test_post_adaptation_skew_rebalances(conn, rng, open_budget_gate):
    """After salting engages, the measured exchange skew of the same
    zipfian stream drops below the salting threshold (~1x)."""
    import re

    s = _mesh_session(conn)
    _load_join_tables(s, _probe_frame("zipf", rng))
    for _ in range(3):
        s.execute(JOIN_Q)
    rendered = s.explain_analyze(JOIN_Q)
    m = re.search(r"Join .*skew ([\d.]+)x", rendered)
    assert m, f"no skew rendered:\n{rendered}"
    assert float(m.group(1)) < 2.0, rendered


@pytest.mark.slow
def test_chaos_adaptive_decisions_in_flight_record(conn, rng,
                                                   monkeypatch,
                                                   open_budget_gate):
    """A failed adaptive run's post-mortem shows what adaptivity
    changed, and the pool drains after the chaos round."""
    from presto_tpu.exec.distributed import DistributedExecutor
    from presto_tpu.runtime.errors import PrestoError
    from presto_tpu.runtime.memory import pool_leaks

    s = _mesh_session(conn, degrade_to_local=False, retry_count=0,
                      oom_ladder_max=0)
    _load_join_tables(s, _probe_frame("zipf", rng))
    for _ in range(3):
        s.execute(JOIN_Q)  # salt becomes sticky
    # fail AFTER the (salted) join executed: the Sort node sits above
    # the join, so by the time it raises the salted exchange already
    # happened and noted its events. Deliberately NOT the fault
    # injector — adaptivity stands down under it, and this test needs
    # the failing run to be a fully adaptive one. The session knobs
    # that could force a late failure (gather_row_limit) are codegen
    # properties and would re-fingerprint the plan away from its
    # history.
    orig = DistributedExecutor._exec_sort

    def boom(self, node, scalars):
        orig(self, node, scalars)
        raise PrestoError("chaos: injected post-join failure")

    monkeypatch.setattr(DistributedExecutor, "_exec_sort", boom)
    with pytest.raises(PrestoError):
        s.execute(JOIN_Q)
    rec = s.flight.latest()
    assert rec is not None and rec.state == "FAILED"
    kinds = {e.get("kind") for e in rec.adaptive}
    assert "salt" in kinds, rec.adaptive
    assert all(e.get("applied") for e in rec.adaptive)
    # the decision log stitched the same run (system.adaptive)
    logged = s.sql("select kind, applied from adaptive "
                   "where kind = 'salt' and applied = 1")
    assert len(logged) >= 1
    assert not pool_leaks(), "chaos round leaked pool reservations"


@pytest.mark.slow
def test_no_decisions_under_success_recorder_runs(conn, rng):
    """flight_record_successes ON: runs record post-mortems, so the
    controller observes the baseline plan only."""
    s = _mesh_session(conn, flight_record_successes=True)
    _load_join_tables(s, _probe_frame("zipf", rng))
    before = _counter("adaptive.salted")
    down = _counter("adaptive.stand_down")
    for _ in range(4):
        s.execute(JOIN_Q)
    assert _counter("adaptive.salted") == before
    assert _counter("adaptive.stand_down") > down
