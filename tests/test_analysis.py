"""The analyzer analyzed: per-rule seeded-violation fixtures.

Every rule family must (a) flag its known-bad snippet, (b) stay silent
on the known-good twin, (c) honor inline suppressions WITH reasons,
(d) honor the reviewed baseline, and (e) emit the stable JSON schema —
the contract tier-1 gate 12 (scripts/lint.sh) builds on. Fixtures are
written to tmp_path and analyzed with that directory as the repo root,
so nothing here touches (or imports) the real engine code: the
analyzer is pure-``ast`` by design and these tests prove it stays so.
"""

import json

import pytest

from presto_tpu.analysis import RULES, analyze
from presto_tpu.analysis.findings import SCHEMA_VERSION


def run(tmp_path, sources: dict, rules=None, baseline=None):
    """Write {filename: source} under tmp_path and analyze it as a
    standalone project (empty baseline unless given)."""
    for name, src in sources.items():
        (tmp_path / name).write_text(src)
    return analyze([str(tmp_path)], root=str(tmp_path), rule_ids=rules,
                   baseline=baseline or [])


def rule_ids(result):
    return [f.rule for f in result.findings]


def test_rule_catalog_registered():
    import presto_tpu.analysis.rules  # noqa: F401

    assert {"PT001", "PT101", "PT102", "PT103", "PT201", "PT301",
            "PT302", "PT303", "PT401", "PT402", "PT403"} <= set(RULES)
    for rid, rule in RULES.items():
        assert rule.description and rule.motivation, rid
        assert rule.severity in ("error", "warning")


# ---------------------------------------------------------------------------
# PT1xx trace hygiene
# ---------------------------------------------------------------------------

BAD_STEP = """
import jax
import numpy as np


def _make_bad_step():
    def step(batch, params=()):
        n = int(batch["count"])
        arr = np.asarray(batch)
        v = batch.item()
        return n + arr + v
    return jax.jit(step)
"""

GOOD_STEP = """
import jax
import jax.numpy as jnp


def _make_good_step(cap):
    def step(batch, params=()):
        rows = int(batch.shape[0])          # static metadata: fine
        fill = float(cap)                   # closure constant: fine
        return jnp.sum(batch) + rows + fill
    return jax.jit(step)
"""


def test_pt101_flags_host_sync_in_traced_step(tmp_path):
    res = run(tmp_path, {"mod.py": BAD_STEP}, rules=["PT101"])
    assert rule_ids(res) == ["PT101", "PT101", "PT101"]
    assert "int(" in res.findings[0].message


def test_pt101_silent_on_static_metadata(tmp_path):
    res = run(tmp_path, {"mod.py": GOOD_STEP}, rules=["PT101"])
    assert res.findings == []


def test_pt102_flags_branch_on_traced_param(tmp_path):
    src = """
import jax


def _make_step():
    def step(batch):
        if batch > 0:
            return batch
        return -batch
    return jax.jit(step)
"""
    res = run(tmp_path, {"mod.py": src}, rules=["PT102"])
    assert rule_ids(res) == ["PT102"]


def test_pt102_silent_on_identity_and_shape_tests(tmp_path):
    src = """
import jax


def _make_step():
    def step(batch, aux=None):
        if aux is not None:
            batch = batch + aux
        if batch.shape[0] > 8:
            batch = batch[:8]
        return batch
    return jax.jit(step)
"""
    res = run(tmp_path, {"mod.py": src}, rules=["PT102"])
    assert res.findings == []


def test_pt103_flags_eval_without_param_scope(tmp_path):
    src = """
from presto_tpu.expr import evaluate


def project(batch, params):
    return evaluate(batch, None)
"""
    good = """
from presto_tpu.expr import evaluate, param_scope


def project(batch, params):
    with param_scope(params):
        return evaluate(batch, None)
"""
    res = run(tmp_path, {"mod.py": src}, rules=["PT103"])
    assert rule_ids(res) == ["PT103"]
    res = run(tmp_path, {"mod.py": good}, rules=["PT103"])
    assert res.findings == []


def test_pt103_flags_param_values_access_outside_expr(tmp_path):
    src = """
from presto_tpu import expr


def peek():
    return expr._PARAM_VALUES.get()
"""
    res = run(tmp_path, {"mod.py": src}, rules=["PT103"])
    assert rule_ids(res) == ["PT103"]
    assert res.findings[0].severity == "error"


# ---------------------------------------------------------------------------
# PT2xx cache-key completeness
# ---------------------------------------------------------------------------

BAD_CACHE = """
import os

from presto_tpu.cache.exec_cache import EXEC_CACHE


def build():
    def builder():
        flag = os.environ.get("PRESTO_TPU_SPECIAL", "0") == "1"
        return lambda b: b if flag else -b
    return EXEC_CACHE.get_or_build(
        EXEC_CACHE.key_of("step", 42), builder)
"""

GOOD_CACHE = """
import os

from presto_tpu.cache.exec_cache import EXEC_CACHE


def build():
    special = os.environ.get("PRESTO_TPU_SPECIAL", "0") == "1"

    def builder():
        return (lambda b: b) if special else (lambda b: -b)
    return EXEC_CACHE.get_or_build(
        EXEC_CACHE.key_of("step", 42, special), builder)
"""


def test_pt201_flags_unkeyed_env_knob(tmp_path):
    res = run(tmp_path, {"mod.py": BAD_CACHE}, rules=["PT201"])
    assert rule_ids(res) == ["PT201"]
    assert "PRESTO_TPU_SPECIAL" in res.findings[0].message


def test_pt201_silent_when_hoisted_knob_is_keyed(tmp_path):
    res = run(tmp_path, {"mod.py": GOOD_CACHE}, rules=["PT201"])
    assert res.findings == []


def test_pt201_flags_captured_knob_missing_from_key(tmp_path):
    src = """
from presto_tpu.cache.exec_cache import EXEC_CACHE
from presto_tpu.spi import narrow_enabled


def build():
    narrow = narrow_enabled()
    return EXEC_CACHE.get_or_build(
        EXEC_CACHE.key_of("step", 7),
        lambda: (lambda b: b + (1 if narrow else 0)))
"""
    res = run(tmp_path, {"mod.py": src}, rules=["PT201"])
    assert rule_ids(res) == ["PT201"]
    assert "narrow_enabled" in res.findings[0].message


def test_pt201_use_pallas_is_implicitly_keyed_via_key_of(tmp_path):
    # key_of itself folds use_pallas() into every fingerprint — a
    # builder reading it with a key_of-built key is complete
    src = """
from presto_tpu.cache.exec_cache import EXEC_CACHE
from presto_tpu.ops.strings import use_pallas


def build():
    return EXEC_CACHE.get_or_build(
        EXEC_CACHE.key_of("step", 7),
        lambda: (lambda b: b if use_pallas() else -b))
"""
    res = run(tmp_path, {"mod.py": src}, rules=["PT201"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# PT3xx lock discipline
# ---------------------------------------------------------------------------

BAD_LOCKS = """
import threading


class Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def drop(self, x):
        self._items.remove(x)
"""

GOOD_LOCKS = """
import threading


class Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, x):
        with self._lock:
            self._items.append(x)
            self._trim_locked()

    def drop(self, x):
        with self._lock:
            self._items.remove(x)

    def _trim_locked(self):
        del self._items[8:]
"""


def test_pt301_flags_unguarded_mutation(tmp_path):
    res = run(tmp_path, {"mod.py": BAD_LOCKS}, rules=["PT301"])
    assert rule_ids(res) == ["PT301"]
    assert "_items" in res.findings[0].message


def test_pt301_honors_locked_suffix_and_init(tmp_path):
    res = run(tmp_path, {"mod.py": GOOD_LOCKS}, rules=["PT301"])
    assert res.findings == []


def test_pt303_flags_self_deadlock_not_rlock(tmp_path):
    src = """
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def reserve(self):
        with self._lock:
            self._n += 1
            return self.describe()

    def describe(self):
        with self._lock:
            return str(self._n)


class RPool:
    def __init__(self):
        self._cv = threading.Condition()   # RLock-backed: reentrant
        self._n = 0

    def reserve(self):
        with self._cv:
            self._n += 1
            return self.describe()

    def describe(self):
        with self._cv:
            return str(self._n)
"""
    res = run(tmp_path, {"mod.py": src}, rules=["PT303"])
    assert rule_ids(res) == ["PT303"]
    assert res.findings[0].data.get("cls") == "Pool" or \
        "Pool" in res.findings[0].message


def test_pt302_flags_lock_order_cycle(tmp_path):
    src = """
import threading


class Alpha:
    def __init__(self, other):
        self._lock = threading.Lock()
        self.other = other

    def ping_alpha(self):
        with self._lock:
            self.other.pong_beta()


class Beta:
    def __init__(self, other):
        self._lock = threading.Lock()
        self.other = other

    def pong_beta(self):
        with self._lock:
            pass

    def back(self):
        with self._lock:
            self.other.ping_alpha()
"""
    res = run(tmp_path, {"mod.py": src}, rules=["PT302"])
    assert rule_ids(res) == ["PT302"]
    assert "Alpha" in res.findings[0].message
    assert "Beta" in res.findings[0].message


def test_pt302_silent_on_one_way_edges(tmp_path):
    src = """
import threading


class Alpha:
    def __init__(self, other):
        self._lock = threading.Lock()
        self.other = other

    def ping_alpha(self):
        with self._lock:
            self.other.pong_beta()


class Beta:
    def __init__(self):
        self._lock = threading.Lock()

    def pong_beta(self):
        with self._lock:
            pass
"""
    res = run(tmp_path, {"mod.py": src}, rules=["PT302"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# PT4xx global-state hygiene
# ---------------------------------------------------------------------------

def test_pt401_flags_unrestored_env_mutation(tmp_path):
    src = """
import os


def test_toggle():
    os.environ["PRESTO_TPU_NARROW"] = "1"
"""
    res = run(tmp_path, {"test_env.py": src}, rules=["PT401"])
    assert rule_ids(res) == ["PT401"]


def test_pt401_honors_try_finally_and_fixture_teardown(tmp_path):
    src = """
import os

import pytest


def test_toggle():
    before = os.environ.get("PRESTO_TPU_NARROW")
    os.environ["PRESTO_TPU_NARROW"] = "1"
    try:
        pass
    finally:
        if before is None:
            os.environ.pop("PRESTO_TPU_NARROW", None)
        else:
            os.environ["PRESTO_TPU_NARROW"] = before


@pytest.fixture
def narrow_env():
    os.environ["PRESTO_TPU_NARROW"] = "1"
    yield
    os.environ.pop("PRESTO_TPU_NARROW", None)
"""
    res = run(tmp_path, {"test_env.py": src}, rules=["PT401"])
    assert res.findings == []


def test_pt401_partial_restore_still_flags_the_unrestored_key(tmp_path):
    src = """
import os


def test_two_keys():
    a = os.environ.get("PRESTO_TPU_A")
    os.environ["PRESTO_TPU_A"] = "1"
    os.environ["PRESTO_TPU_B"] = "1"
    try:
        pass
    finally:
        os.environ.pop("PRESTO_TPU_A", None)
"""
    res = run(tmp_path, {"test_env.py": src}, rules=["PT401"])
    assert rule_ids(res) == ["PT401"]
    assert "PRESTO_TPU_B" in res.findings[0].message


def test_pt303_flags_acquire_release_style_hold(tmp_path):
    src = """
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def reserve(self):
        self._lock.acquire()
        try:
            self._n += 1
            return self.describe()
        finally:
            self._lock.release()

    def describe(self):
        with self._lock:
            return str(self._n)
"""
    res = run(tmp_path, {"mod.py": src}, rules=["PT303"])
    assert rule_ids(res) == ["PT303"]


def test_pt402_requires_marker_for_registry_reset(tmp_path):
    bad = """
from presto_tpu.runtime.metrics import REGISTRY


def test_counters():
    REGISTRY.reset()
"""
    good = """
import pytest

from presto_tpu.runtime.metrics import REGISTRY


@pytest.mark.resets_global_state
def test_counters():
    REGISTRY.reset()
"""
    pytestmarked = """
import pytest

from presto_tpu.runtime.metrics import REGISTRY

pytestmark = pytest.mark.resets_global_state


def test_counters():
    REGISTRY.reset()
"""
    res = run(tmp_path, {"test_reg.py": bad}, rules=["PT402"])
    assert rule_ids(res) == ["PT402"]
    res = run(tmp_path, {"test_reg.py": good}, rules=["PT402"])
    assert res.findings == []
    # module-level pytestmark is the same declaration surface the
    # runtime conftest guard accepts — the static rule must agree
    res = run(tmp_path, {"test_reg.py": pytestmarked}, rules=["PT402"])
    assert res.findings == []


def test_pt403_flags_raw_trace_probe_outside_window(tmp_path):
    bad = """
from presto_tpu.runtime.metrics import REGISTRY


def test_warm(session):
    t0 = REGISTRY.snapshot().get("exec.traces", 0)
    session.sql("select 1")
    assert REGISTRY.snapshot().get("exec.traces", 0) == t0
"""
    good = """
from presto_tpu.cache.exec_cache import trace_delta


def test_warm(session):
    with trace_delta() as td:
        session.sql("select 1")
    assert td.traces == 0
"""
    res = run(tmp_path, {"test_tr.py": bad}, rules=["PT403"])
    assert rule_ids(res) == ["PT403", "PT403"]
    res = run(tmp_path, {"test_tr.py": good}, rules=["PT403"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# suppression / baseline / output schema
# ---------------------------------------------------------------------------

def test_suppression_with_reason_is_honored(tmp_path):
    src = BAD_LOCKS.replace(
        "        self._items.remove(x)",
        "        # presto-lint: ignore[PT301] -- benchmark-only path, "
        "single-threaded by construction\n"
        "        self._items.remove(x)")
    res = run(tmp_path, {"mod.py": src}, rules=["PT301"])
    assert res.findings == []
    assert len(res.suppressed) == 1
    assert "single-threaded" in res.suppressed[0][1].reason


def test_suppression_without_reason_does_not_suppress(tmp_path):
    src = BAD_LOCKS.replace(
        "        self._items.remove(x)",
        "        self._items.remove(x)  # presto-lint: ignore[PT301]")
    res = run(tmp_path, {"mod.py": src})
    ids = rule_ids(res)
    assert "PT301" in ids      # not suppressed
    assert "PT001" in ids      # and the reasonless comment is flagged


def test_baseline_is_honored_and_content_anchored(tmp_path):
    res = run(tmp_path, {"mod.py": BAD_LOCKS}, rules=["PT301"])
    (finding,) = res.findings
    entry = {"rule": "PT301", "path": finding.path,
             "anchor": finding.anchor,
             "reason": "grandfathered: pre-lint code, scheduled fix"}
    res2 = run(tmp_path, {"mod.py": BAD_LOCKS}, rules=["PT301"],
               baseline=[entry])
    assert res2.findings == [] and len(res2.baselined) == 1
    # editing the flagged line orphans the entry: the finding returns
    drifted = dict(entry, anchor="self._items.remove(x, strict=True)")
    res3 = run(tmp_path, {"mod.py": BAD_LOCKS}, rules=["PT301"],
               baseline=[drifted])
    assert rule_ids(res3) == ["PT301"]


def test_json_output_schema_is_stable(tmp_path):
    res = run(tmp_path, {"mod.py": BAD_LOCKS}, rules=["PT301"])
    doc = json.loads(res.to_json())
    assert doc["version"] == SCHEMA_VERSION
    assert set(doc["counts"]) == {"open", "suppressed", "baselined"}
    (f,) = doc["findings"]
    assert set(f) == {"rule", "severity", "path", "line", "col",
                      "message", "hint", "anchor", "data"}
    assert f["rule"] == "PT301" and f["path"] == "mod.py"
    assert isinstance(f["line"], int) and f["line"] > 0
    assert f["data"] == {"cls": "Shared", "attr": "_items"}


def test_cli_exit_codes_and_rule_filter(tmp_path, capsys):
    from presto_tpu.analysis.__main__ import main

    (tmp_path / "mod.py").write_text(BAD_LOCKS)
    rc = main([str(tmp_path / "mod.py"), "--no-baseline"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "PT301" in out
    rc = main([str(tmp_path / "mod.py"), "--no-baseline",
               "--rule", "PT403"])
    assert rc == 0
    assert main(["--list-rules"]) == 0


def test_unknown_rule_id_is_a_usage_error(tmp_path, capsys):
    from presto_tpu.analysis.__main__ import main

    assert main(["--rule", "PT999", str(tmp_path)]) == 2


def test_repo_analyzes_clean():
    """The acceptance gate in miniature: the shipped tree has zero
    unsuppressed findings against the shipped baseline."""
    import os

    import presto_tpu

    root = os.path.dirname(os.path.dirname(
        os.path.abspath(presto_tpu.__file__)))
    res = analyze([os.path.join(root, "presto_tpu"),
                   os.path.join(root, "tests")], root=root)
    assert res.findings == [], [f.render() for f in res.findings]
