"""Batch/Column/Dictionary unit tests (reference parity: presto-common
block tests / BlockAssertions [SURVEY §4])."""

import jax
import numpy as np
import pytest

from presto_tpu import BIGINT, DOUBLE, Batch, Dictionary, decimal, varchar
from presto_tpu.types import DATE, INTEGER, TypeKind


def make_batch(n=10, cap=16):
    types = {
        "k": BIGINT,
        "price": decimal(12, 2),
        "flag": varchar(),
    }
    d = Dictionary(["A", "N", "R"])
    arrays = {
        "k": np.arange(n, dtype=np.int64),
        "price": (np.arange(n) * 100 + 50),
        "flag": d.encode(["A", "N", "R", "A", "N", "R", "A", "N", "R", "A"][:n]),
    }
    return Batch.from_numpy(arrays, types, capacity=cap, dictionaries={"flag": d})


def test_roundtrip_pandas():
    b = make_batch()
    df = b.to_pandas()
    assert len(df) == 10
    assert df["price"].iloc[3] == 3.50
    assert df["flag"].iloc[2] == "R"


def test_capacity_padding_and_live():
    b = make_batch(n=10, cap=16)
    assert b.capacity == 16
    assert int(b.count()) == 10
    assert not bool(b.live[10])


def test_pytree_roundtrip():
    b = make_batch()
    leaves, treedef = jax.tree_util.tree_flatten(b)
    b2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert b2.names == b.names
    assert b2["flag"].dictionary is b["flag"].dictionary
    assert int(b2.count()) == 10


def test_batch_through_jit():
    b = make_batch()

    @jax.jit
    def double_price(batch: Batch) -> Batch:
        c = batch["price"]
        from presto_tpu.batch import Column

        return batch.with_column("price2", Column(c.data * 2, c.valid, c.dtype))

    out = double_price(b)
    df = out.to_pandas()
    assert df["price2"].iloc[1] == 3.0  # 1.50 * 2


def test_ordered_dictionary():
    d = Dictionary(["delta", "alpha", "charlie"])
    assert list(d.values) == ["alpha", "charlie", "delta"]
    assert d.code_of("charlie") == 1
    assert d.lower_bound("b") == 1
    assert d.lower_bound("zz") == 3
    np.testing.assert_array_equal(
        d.encode(["delta", "alpha"]), np.array([2, 0], dtype=np.int32)
    )


def test_null_mask():
    types = {"x": INTEGER}
    b = Batch.from_numpy(
        {"x": np.array([1, 2, 3])},
        types,
        valids={"x": np.array([True, False, True])},
    )
    df = b.to_pandas()
    assert df["x"].iloc[1] is None
