"""Interval inference (plan/bounds.py) + the value_bits runtime guard.

Reference parity: stats-driven operator specialization — the analog of
the reference feeding StatsCalculator estimates into physical-operator
choices [SURVEY §2.1 optimizer row]; here the stat shapes the fused
segment-sum's lane count, with a runtime overflow guard + 63-bit retry
making wrong stats harmless.
"""

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.plan.bounds import agg_value_bits, expr_interval, node_intervals
from presto_tpu.runtime.session import Session
from presto_tpu.expr import Call, col, lit
from presto_tpu.types import BIGINT, BOOLEAN, decimal


dec2 = decimal(12, 2)
dec4 = decimal(38, 4)


@pytest.fixture(scope="module")
def session():
    return Session({"tpch": TpchConnector(sf=0.01)})


def test_expr_interval_arithmetic():
    env = {"a": (0, 100), "b": (-10, 10)}
    assert expr_interval(col("a", BIGINT), env) == (0, 100)
    assert expr_interval(
        Call(BIGINT, "add", (col("a", BIGINT), col("b", BIGINT))), env
    ) == (-10, 110)
    assert expr_interval(
        Call(BIGINT, "sub", (col("a", BIGINT), col("b", BIGINT))), env
    ) == (-10, 110)
    assert expr_interval(
        Call(BIGINT, "mul", (col("a", BIGINT), col("b", BIGINT))), env
    ) == (-1000, 1000)
    assert expr_interval(
        Call(BIGINT, "neg", (col("a", BIGINT),)), env
    ) == (-100, 0)
    assert expr_interval(
        Call(BIGINT, "abs", (col("b", BIGINT),)), env
    ) == (0, 10)
    # unknown column -> unbounded
    assert expr_interval(col("zzz", BIGINT), env) is None


def test_expr_interval_decimal_rescale():
    # dec2 column times (1 - dec2 discount): the Q1 disc_price shape.
    env = {"price": (90_000, 10_495_000), "disc": (0, 10)}
    one = lit(1, dec2)
    disc_price = Call(
        dec4,
        "mul",
        (col("price", dec2), Call(dec2, "sub", (one, col("disc", dec2)))),
    )
    iv = expr_interval(disc_price, env)
    assert iv is not None
    lo, hi = iv
    # physical scale 4: max = 10_495_000 * 100 (1.00 at scale 2)
    assert hi == 10_495_000 * 100
    assert lo >= 0
    # literals evaluate at their physical scale
    assert expr_interval(one, {}) == (100, 100)


def test_expr_interval_case_shapes():
    env = {"x": (0, 5)}
    cond = Call(BOOLEAN, "gt", (col("x", BIGINT), lit(2, BIGINT)))
    # if(cond, x, 100)
    e = Call(BIGINT, "if", (cond, col("x", BIGINT), lit(100, BIGINT)))
    assert expr_interval(e, env) == (0, 100)
    # case without else includes the physical fill 0
    e2 = Call(BIGINT, "case", (cond, lit(-7, BIGINT)))
    assert expr_interval(e2, env) == (-7, 0)


def test_scan_intervals_from_connector_stats(session):
    plan = session.plan("select l_quantity, l_extendedprice, l_shipdate from lineitem")
    from presto_tpu.plan import nodes as N

    node = plan
    while not isinstance(node, N.TableScan):
        node = node.children[0]
    iv = node_intervals(node, session.catalog)
    # l_quantity DECIMAL(12,2): [1, 50] -> physical [100, 5000]
    assert iv["l_quantity"] == (100, 5000)
    # l_shipdate DATE: day-number interval
    assert iv["l_shipdate"] == (8035, 10591)
    assert iv["l_extendedprice"][1] <= 10_495_000 + 1


def test_q1_sql_gets_tight_value_bits(session):
    """The SQL Q1 plan's sums carry stats-derived bounds (<= 35 bits),
    not the 63-bit default (VERDICT r2 weak #7)."""
    from presto_tpu.plan import nodes as N

    plan = session.plan(
        "select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty, "
        "sum(l_extendedprice) as sum_base_price, "
        "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
        "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge, "
        "count(*) as count_order "
        "from lineitem where l_shipdate <= date '1998-09-02' "
        "group by l_returnflag, l_linestatus"
    )
    node = plan
    while not isinstance(node, N.Aggregate):
        node = node.children[0]
    bits = agg_value_bits(node, session.catalog)
    sums = [b for a, b in zip(node.aggs, bits) if a.kind == "sum"]
    # qty, base_price, disc_price, charge — in select-list order
    assert sums[0] <= 13
    assert sums[1] <= 24
    assert sums[2] <= 31
    assert sums[3] <= 41
    assert all(b < 63 for b in sums)


def test_value_bits_violation_retries_correctly(session):
    """A deliberately wrong (too-tight) stat bound must not produce a
    wrong answer: the runtime guard trips and the executor retries on
    the 63-bit path."""
    import presto_tpu.plan.bounds as B

    real = B.agg_value_bits

    def lying(agg, catalog):
        return [1 for _ in agg.aggs]  # absurdly tight: 1 bit per value

    B.agg_value_bits = lying
    try:
        got = session.sql(
            "select l_returnflag, sum(l_quantity) as s from lineitem "
            "group by l_returnflag order by l_returnflag"
        )
    finally:
        B.agg_value_bits = real
    want = session.sql(
        "select l_returnflag, sum(l_quantity) as s from lineitem "
        "group by l_returnflag order by l_returnflag"
    )
    assert got.equals(want)


# ---------------------------------------------------------------------------
# per-walk memoization (ISSUE-9 satellite): pure — identical results,
# linear instead of quadratic estimate walks
# ---------------------------------------------------------------------------


def test_estimate_memo_is_pure(session):
    from presto_tpu.connectors.tpch.queries import QUERIES
    from presto_tpu.plan.bounds import estimate_record, estimate_rows

    plan = session.plan(QUERIES["q3"])
    memo: dict = {}

    def walk(n):
        assert estimate_rows(n, session.catalog, memo) == estimate_rows(
            n, session.catalog)
        assert node_intervals(n, session.catalog, memo) == node_intervals(
            n, session.catalog)
        assert estimate_record(n, session.catalog, memo=memo) == \
            estimate_record(n, session.catalog)
        for c in n.children:
            walk(c)

    walk(plan)
    assert memo  # the walk actually populated (and reused) the memo


def test_estimate_memo_hits_shared_subtrees(session):
    from presto_tpu.connectors.tpch.queries import QUERIES
    from presto_tpu.plan.bounds import estimate_rows

    plan = session.plan(QUERIES["q3"])
    memo: dict = {}
    estimate_rows(plan, session.catalog, memo)
    n_entries = len([k for k in memo if k[0] == "rows"])
    # a second full-tree call is answered entirely from the memo
    estimate_rows(plan, session.catalog, memo)
    assert len([k for k in memo if k[0] == "rows"]) == n_entries


def test_estimate_groups_from_ndv(session):
    from presto_tpu.plan.bounds import estimate_groups
    from presto_tpu.plan import nodes as N

    plan = session.plan(
        "select l_orderkey, count(*) c from lineitem group by l_orderkey")

    def find_agg(n):
        if isinstance(n, N.Aggregate):
            return n
        for c in n.children:
            r = find_agg(c)
            if r is not None:
                return r

    agg = find_agg(plan)
    g = estimate_groups(agg, session.catalog)
    assert g is not None and g > 1
    # clamped by the child's estimated rows
    from presto_tpu.plan.bounds import estimate_rows

    assert g <= estimate_rows(agg.child, session.catalog)
