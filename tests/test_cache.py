"""Query caching subsystem (presto_tpu/cache/): fingerprints,
compiled-executable cache, versioned result cache, stats cache.

Reference parity: prepared-plan reuse + fragment-result caching
(RaptorX) + the worker-side expression-compiler caches [SURVEY §2.1].
Covers the ISSUE-2 acceptance matrix: cold/warm no-retrace, bitwise
result-cache hits, DDL invalidation, byte-budget LRU eviction, failed /
fault-injected queries never populating, and the enabled=false bypass.
"""

import pandas as pd
import pytest

from presto_tpu.batch import Dictionary
from presto_tpu.cache.exec_cache import (
    EXEC_CACHE,
    ExecutableCache,
    trace_delta,
)
from presto_tpu.cache.fingerprint import (
    dictionary_fingerprint,
    fingerprint,
    plan_fingerprint,
    plan_is_deterministic,
    referenced_tables,
    try_fingerprint,
)
from presto_tpu.cache.result_cache import ResultCache, frame_bytes
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.expr import BIGINT, InputRef
from presto_tpu.runtime.faults import FaultInjector, injected
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

CONN = TpchConnector(sf=0.01)

AGG_JOIN_SQL = (
    "select n_name, count(*) c, sum(s_acctbal) b "
    "from supplier join nation on s_nationkey = n_nationkey "
    "group by n_name order by n_name"
)


def make_session(**props):
    return Session({"tpch": CONN}, properties=props or None)


def counter(name: str) -> float:
    return REGISTRY.snapshot().get(name, 0.0)


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------


def test_dictionary_hashes_by_content_not_identity():
    d1 = Dictionary(["a", "b", "c"])
    d2 = Dictionary(["a", "b", "c"])
    d3 = Dictionary(["a", "b", "x"])
    assert d1 is not d2
    assert dictionary_fingerprint(d1) == dictionary_fingerprint(d2)
    assert dictionary_fingerprint(d1) != dictionary_fingerprint(d3)
    assert fingerprint(d1) == fingerprint(d2)
    assert fingerprint(d1) != fingerprint(d3)


def test_fingerprint_distinguishes_structure():
    assert fingerprint((1, 2), 3) != fingerprint((1, 2, 3))
    assert fingerprint("12") != fingerprint(12)
    assert fingerprint([1, [2]]) != fingerprint([[1], 2])
    assert try_fingerprint(object()) is None  # uncacheable, never a guess


def test_identical_sql_has_identical_plan_fingerprint():
    s = make_session()
    fp1 = plan_fingerprint(s.plan(AGG_JOIN_SQL), s.catalog, s.properties)
    fp2 = plan_fingerprint(s.plan(AGG_JOIN_SQL), s.catalog, s.properties)
    assert fp1 is not None and fp1 == fp2
    # a different query, and a codegen-affecting property, change it
    fp3 = plan_fingerprint(
        s.plan(AGG_JOIN_SQL.replace("count(*)", "count(*) + 1")),
        s.catalog, s.properties,
    )
    assert fp3 != fp1
    fp4 = plan_fingerprint(s.plan(AGG_JOIN_SQL), s.catalog,
                           {"direct_group_limit": 7})
    assert fp4 != fp1


def test_slotted_plan_fingerprints_identically_across_reanalysis():
    """The gensym discipline extended to plan templates: parameterized
    re-analysis of identical SQL must produce an identical template
    fingerprint (slot ids are deterministic pre-order ordinals, like
    gensyms), and two statements differing ONLY in eligible literals
    must share ONE template fingerprint — that identity is the whole
    compiled-executable reuse story."""
    from presto_tpu.plan.templates import parameterize_plan

    s = make_session()
    fmt = ("select l_orderkey, l_linenumber, l_quantity + {} q"
           " from lineitem where l_extendedprice < {}"
           " order by l_orderkey, l_linenumber limit 10")

    def template_fp(sql):
        plan, slots = parameterize_plan(
            s.plan(sql), s.catalog)
        assert slots  # the sweep literals really did slot
        return plan_fingerprint(plan, s.catalog, s.properties), slots

    fp1, slots1 = template_fp(fmt.format(3, 2000))
    fp2, slots2 = template_fp(fmt.format(3, 2000))  # re-analysis
    assert fp1 is not None and fp1 == fp2
    assert [(x.slot, x.dtype) for x in slots1] == \
        [(x.slot, x.dtype) for x in slots2]
    fp3, slots3 = template_fp(fmt.format(7, 90000))  # new literals only
    assert fp3 == fp1
    assert [x.value for x in slots3] != [x.value for x in slots1]
    # explicit ?-placeholder plans fingerprint identically too (the
    # PREPARE path: user slots precede auto slots deterministically)
    psql = ("select count(*) c from orders"
            " where o_orderkey between ? and ?")
    h1 = s.prepare(psql)
    h2 = s.prepare(psql)
    assert plan_fingerprint(h1.plan, s.catalog, s.properties) == \
        plan_fingerprint(h2.plan, s.catalog, s.properties)


def test_table_version_bump_changes_plan_fingerprint():
    s = make_session()
    fp1 = plan_fingerprint(s.plan("select count(*) c from region"),
                           s.catalog, s.properties)
    s.catalog.invalidate("region")
    fp2 = plan_fingerprint(s.plan("select count(*) c from region"),
                           s.catalog, s.properties)
    assert fp1 != fp2


def test_system_table_plans_are_volatile():
    s = make_session()
    plan = s.plan("select name from runtime_metrics")
    assert ("system", "runtime_metrics") in referenced_tables(plan)
    assert not plan_is_deterministic(plan, s.catalog)
    assert plan_is_deterministic(s.plan("select count(*) c from region"),
                                 s.catalog)


# ---------------------------------------------------------------------------
# executable cache
# ---------------------------------------------------------------------------


def test_exec_cache_get_or_build_and_lru_eviction():
    c = ExecutableCache(max_entries=2)
    builds = []

    def build(tag):
        def b():
            builds.append(tag)
            return tag

        return b

    assert c.get_or_build(c.key_of("a"), build("a")) == "a"
    assert c.get_or_build(c.key_of("a"), build("a2")) == "a"  # hit
    assert builds == ["a"]
    c.get_or_build(c.key_of("b"), build("b"))
    c.get_or_build(c.key_of("a"), build("a3"))  # refresh a's recency
    c.get_or_build(c.key_of("c"), build("c"))  # evicts b (LRU-first)
    assert c.get_or_build(c.key_of("a"), build("a4")) == "a"
    assert builds == ["a", "b", "c"]
    assert c.get_or_build(c.key_of("b"), build("b2")) == "b2"  # rebuilt
    assert builds == ["a", "b", "c", "b2"]
    # an unfingerprintable key falls back to building uncached
    assert c.get_or_build(None, build("u")) == "u"
    assert c.get_or_build(None, build("u2")) == "u2"


def test_exec_cache_key_folds_pallas_setting(monkeypatch):
    """Step bodies read use_pallas() at trace time, so the kernel
    choice is baked into the compiled step — the key must separate the
    two worlds or flipping pallas_strings is inert on warm hits."""
    monkeypatch.setenv("PRESTO_TPU_PALLAS", "0")
    k0 = EXEC_CACHE.key_of("probe")
    monkeypatch.setenv("PRESTO_TPU_PALLAS", "1")
    k1 = EXEC_CACHE.key_of("probe")
    assert k0 is not None and k0 != k1


def test_warm_identical_query_does_not_retrace():
    """The tentpole assertion: a second identical query (fresh session,
    result cache off so the pipeline really executes) is served
    entirely from jit signature caches — zero re-traces."""
    s1 = make_session(result_cache_enabled=False)
    df1 = s1.sql(AGG_JOIN_SQL)
    s2 = make_session(result_cache_enabled=False)
    hits0 = counter("exec_cache.hit")
    with trace_delta() as td:
        df2 = s2.sql(AGG_JOIN_SQL)
    assert td.traces == 0  # no re-trace at all
    assert counter("exec_cache.hit") > hits0
    pd.testing.assert_frame_equal(df1, df2)


# ---------------------------------------------------------------------------
# result cache: unit level
# ---------------------------------------------------------------------------


def _df(tag: int, rows: int = 64) -> pd.DataFrame:
    return pd.DataFrame({"x": range(tag, tag + rows)})


def test_result_cache_byte_budget_evicts_lru_first():
    s = make_session()  # real catalog for the version re-check
    one = frame_bytes(_df(0))
    rc = ResultCache(max_bytes=2 * one + one // 2)  # fits exactly two
    rc.put("a", _df(1), (("t", 0),))
    rc.put("b", _df(2), (("t", 0),))
    assert rc.get("a", s.catalog) is not None  # refresh a's recency
    ev0 = counter("result_cache.evicted")
    rc.put("c", _df(3), (("t", 0),))  # evicts b, the LRU entry
    assert counter("result_cache.evicted") == ev0 + 1
    assert rc.get("b", s.catalog) is None
    assert rc.get("a", s.catalog) is not None
    assert rc.get("c", s.catalog) is not None
    assert rc.bytes_used <= rc.max_bytes
    # an over-budget frame is skipped, not stored
    sk0 = counter("result_cache.skipped")
    assert not rc.put("huge", _df(9, rows=100_000), (("t", 0),))
    assert counter("result_cache.skipped") == sk0 + 1
    assert rc.get("huge", s.catalog) is None


def test_result_cache_version_drift_drops_entry():
    s = make_session()
    rc = ResultCache(max_bytes=1 << 20)
    rc.put("k", _df(1), (("region", s.catalog.version("region")),))
    assert rc.get("k", s.catalog) is not None
    s.catalog.invalidate("region")
    inv0 = counter("result_cache.invalidated")
    assert rc.get("k", s.catalog) is None
    assert counter("result_cache.invalidated") == inv0 + 1
    assert len(rc) == 0


def test_result_cache_returns_defensive_copies():
    s = make_session()
    rc = ResultCache(max_bytes=1 << 20)
    src = _df(1)
    rc.put("k", src, ())
    out = rc.get("k", s.catalog)
    out.loc[:, "x"] = -1
    again = rc.get("k", s.catalog)
    assert again["x"].tolist() == src["x"].tolist()


# ---------------------------------------------------------------------------
# result cache: end to end
# ---------------------------------------------------------------------------


def test_warm_query_is_result_cache_hit_bitwise_identical():
    s = make_session()
    df1 = s.sql(AGG_JOIN_SQL)
    hit0 = counter("result_cache.hit")
    df2 = s.sql(AGG_JOIN_SQL)
    assert counter("result_cache.hit") == hit0 + 1
    pd.testing.assert_frame_equal(df1, df2)  # dtypes + values, exact
    info = s.query_history[-1]
    assert info.cache_hit and info.state == "FINISHED"
    import json

    assert json.loads(info.to_json())["cacheHit"] is True


def test_result_cache_hit_skips_execution_entirely():
    s = make_session()
    s.sql(AGG_JOIN_SQL)
    started0 = counter("query.started")
    execs = []
    orig = s._make_executor
    s._make_executor = lambda: execs.append(1) or orig()
    with trace_delta() as td:
        s.sql(AGG_JOIN_SQL)
    assert execs == []  # no executor was even constructed
    assert td.traces == 0
    assert counter("query.started") == started0 + 1  # still tracked


def test_query_cached_event_fires():
    s = make_session()

    class L:
        cached = []
        completed = []

        def query_cached(self, info):
            self.cached.append(info.query_id)

        def query_completed(self, info):
            self.completed.append(info.query_id)

    s.events.add(L())
    s.sql("select count(*) c from region")
    assert L.cached == []
    s.sql("select count(*) c from region")
    assert len(L.cached) == 1
    # a cached query still reaches the terminal query_completed event
    assert L.cached[0] == L.completed[-1]


def test_explain_analyze_reports_cache_hit():
    s = make_session()
    q = "select count(*) c from nation"
    first = s.explain_analyze(q)
    assert "result cache: HIT" not in first
    second = s.explain_analyze(q)
    assert second.startswith("result cache: HIT (no execution)")


def test_result_cache_disabled_bypasses_cleanly():
    s = make_session(result_cache_enabled=False)
    hit0 = counter("result_cache.hit")
    pop0 = counter("result_cache.populated")
    df1 = s.sql(AGG_JOIN_SQL)
    df2 = s.sql(AGG_JOIN_SQL)
    pd.testing.assert_frame_equal(df1, df2)
    assert counter("result_cache.hit") == hit0
    assert counter("result_cache.populated") == pop0
    assert len(s.result_cache) == 0
    assert not s.query_history[-1].cache_hit


def test_volatile_system_queries_never_cached():
    s = make_session()
    s.sql("select name, value from runtime_metrics")
    hit0 = counter("result_cache.hit")
    s.sql("select name, value from runtime_metrics")
    assert counter("result_cache.hit") == hit0
    assert len(s.result_cache) == 0


def test_fault_injected_runs_never_populate():
    s = make_session()
    pop0 = counter("result_cache.populated")
    with injected(FaultInjector()):  # armed-but-quiet injector
        df1 = s.sql("select count(*) c from region")
        df2 = s.sql("select count(*) c from region")
    pd.testing.assert_frame_equal(df1, df2)
    assert counter("result_cache.populated") == pop0
    assert len(s.result_cache) == 0


def test_failed_queries_never_populate():
    s = make_session()

    class Boom:
        recorder = None

        def run(self, plan):
            raise RuntimeError("exec failure")

    s._make_executor = lambda: Boom()
    pop0 = counter("result_cache.populated")
    with pytest.raises(RuntimeError, match="exec failure"):
        s.sql("select count(*) c from region")
    assert counter("result_cache.populated") == pop0
    assert len(s.result_cache) == 0
    assert s.query_history[-1].state == "FAILED"


def test_result_caches_are_per_session():
    """Equal fingerprints across sessions do NOT imply equal data:
    private memory catalogs may hold different rows under one name."""
    s1 = make_session()
    s2 = make_session()
    s1.sql("create table private as select 1 x")
    s2.sql("create table private as select 2 x")
    q = "select x from private"
    assert int(s1.sql(q)["x"][0]) == 1
    assert int(s1.sql(q)["x"][0]) == 1  # warm in s1
    assert int(s2.sql(q)["x"][0]) == 2  # never served s1's entry


def test_shared_agg_step_keeps_per_trace_dictionaries():
    """Regression: operators sharing one cached agg step must each see
    the dictionaries of THEIR OWN trace signature. A shared side-dict
    would hand a signature-cache hit the most recent trace's
    dictionary — decoding one session's group keys with another
    session's strings."""
    q = "select x, count(*) c from t group by x order by x"
    s1 = make_session()
    s1.sql("create table t as select 'aa' x union all select 'bb' x")
    s2 = make_session()
    s2.sql("create table t as select 'yy' x union all select 'zz' x")
    assert s1.sql(q)["x"].tolist() == ["aa", "bb"]
    assert s2.sql(q)["x"].tolist() == ["yy", "zz"]  # same step fingerprint
    s3 = make_session()  # fresh session, signature hit on s1's trace
    s3.sql("create table t as select 'aa' x union all select 'bb' x")
    assert s3.sql(q)["x"].tolist() == ["aa", "bb"]


# ---------------------------------------------------------------------------
# DDL invalidation
# ---------------------------------------------------------------------------


def test_ctas_insert_drop_invalidate_result_cache():
    s = make_session()
    s.sql("create table t as select 1 a union all select 2 a")
    q = "select sum(a) s from t"
    assert int(s.sql(q)["s"][0]) == 3
    hit0 = counter("result_cache.hit")
    assert int(s.sql(q)["s"][0]) == 3  # warm: served from cache
    assert counter("result_cache.hit") == hit0 + 1
    s.sql("insert into t select 10 a")
    assert int(s.sql(q)["s"][0]) == 13  # stale 3 is impossible
    s.sql("drop table t")
    s.sql("create table t as select 100 a")
    assert int(s.sql(q)["s"][0]) == 100


def test_stale_metadata_read_after_ctas_impossible():
    """Regression (satellite #2): every DDL path — SQL or direct
    Python-API writes on the memory connector — must bump the catalog
    version and drop cached TableMeta."""
    s = make_session()
    s.sql("create table m as select 1 a")
    v1 = s.catalog.version("m")
    assert v1 == 1  # exactly ONE bump per DDL statement
    meta1 = s.catalog.resolve("m")
    assert meta1.row_count == 1
    s.sql("insert into m select 2 a")
    assert s.catalog.version("m") == v1 + 1
    assert s.catalog.resolve("m").row_count == 2  # not the cached meta
    # direct Python-API write (bypasses SQL DDL) still bumps
    mem = s.catalog.connector("memory")
    v2 = s.catalog.version("direct")
    mem.create_table("direct", pd.DataFrame({"z": [1, 2, 3]}))
    assert s.catalog.version("direct") > v2
    assert s.catalog.resolve("direct").row_count == 3
    mem.drop_table("direct")
    assert s.catalog.version("direct") > v2 + 1


def test_ddl_forces_full_miss_then_recaches():
    s = make_session()
    s.sql("create table r as select 5 v")
    q = "select v from r"
    s.sql(q)
    s.sql(q)  # warm
    miss0 = counter("result_cache.miss")
    s.sql("insert into r select 6 v")
    df = s.sql(q)  # full miss: recomputed
    assert counter("result_cache.miss") > miss0
    assert sorted(df["v"].tolist()) == [5, 6]
    hit0 = counter("result_cache.hit")
    s.sql(q)  # and the recomputed result re-caches
    assert counter("result_cache.hit") == hit0 + 1


# ---------------------------------------------------------------------------
# stats cache (promoted joinkeys min/max readbacks)
# ---------------------------------------------------------------------------


def test_stats_cache_content_keyed_and_version_invalidated():
    from presto_tpu.cache import stats_cache

    s = make_session()
    plan_a = s.plan("select l_partkey from lineitem")
    plan_b = s.plan("select l_partkey from lineitem")  # distinct object
    expr = InputRef(BIGINT, "l_partkey")
    k1 = stats_cache.minmax_key(s.catalog, plan_a, expr)
    k2 = stats_cache.minmax_key(s.catalog, plan_b, expr)
    assert k1 is not None and k1 == k2  # content, not identity
    calls = []
    v1 = stats_cache.cached_minmax(k1, lambda: (calls.append(1), (0, 7))[1])
    v2 = stats_cache.cached_minmax(k2, lambda: (calls.append(1), (9, 9))[1])
    assert v1 == v2 == (0, 7) and calls == [1]  # one readback, reused
    s.catalog.invalidate("lineitem")
    k3 = stats_cache.minmax_key(s.catalog, plan_a, expr)
    assert k3 != k1  # DDL bump forces a fresh probe
    # two sessions' same-named tables never share entries
    s2 = make_session()
    k4 = stats_cache.minmax_key(s2.catalog, s2.plan(
        "select l_partkey from lineitem"), expr)
    assert k4 != k2


def test_stats_cache_unbound_scalar_subtrees_uncacheable():
    """A subtree filtered by a scalar subquery reads values bound from
    a SIBLING subplan — the fingerprint cannot see them, so the probe
    must stay uncacheable (stale min/max would mis-pack join keys)."""
    from presto_tpu.cache import stats_cache

    s = make_session()
    plan = s.plan("select l_partkey from lineitem "
                  "where l_quantity <= (select max(p_size) from part)")
    expr = InputRef(BIGINT, "l_partkey")
    assert stats_cache.minmax_key(s.catalog, plan, expr) is None


# ---------------------------------------------------------------------------
# surfacing
# ---------------------------------------------------------------------------


def test_counters_surface_through_system_runtime_metrics():
    s = make_session()
    s.sql(AGG_JOIN_SQL)
    s.sql(AGG_JOIN_SQL)
    df = s.sql("select name, value from runtime_metrics")
    names = {n.rstrip() for n in df["name"].tolist()}
    assert {"result_cache.hit", "result_cache.miss",
            "result_cache.populated", "exec_cache.hit",
            "exec_cache.miss", "exec.traces"} <= names
    vals = {n.rstrip(): v for n, v in zip(df["name"], df["value"])}
    assert vals["result_cache.hit"] >= 1
    assert vals["exec_cache.hit"] >= 1


@pytest.mark.resets_global_state
def test_exec_cache_max_entries_property_applies():
    # marked: lowering the bound to 8 EVICTS the process-wide warm
    # executables even though the bound itself is restored below —
    # later tests recompile, and the conftest guard wants that declared
    prior = EXEC_CACHE.max_entries
    try:
        s = make_session(exec_cache_max_entries=8)
        s.sql("select count(*) c from region")
        assert EXEC_CACHE.max_entries == 8
        # the cache is process-wide: a session that never set the knob
        # must not touch (or reset) the bound another session chose
        s2 = make_session()
        s2.sql("select count(*) c from region")
        assert EXEC_CACHE.max_entries == 8
    finally:
        EXEC_CACHE.set_max_entries(prior)
