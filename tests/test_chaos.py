"""Seeded chaos suite: randomized fault schedules against a correctness
oracle.

The robustness contract this PR closes (ISSUE 4): under ANY injected
failure schedule — transient faults, backend-shaped OOMs at jitted-step
dispatch, tiny memory pools, concurrent sessions — the engine must
never return a WRONG answer. Every run either matches the fault-free
oracle or fails with a typed taxonomy error; the memory pool balance
returns to zero (no reservation leaks); nothing hangs unboundedly.

Determinism: each round derives its whole schedule (query, session
properties, fault specs) from one integer seed via a private
``random.Random``, and the ``FaultInjector`` draws probability faults
from its own seeded stream — same seed, same run. The tier-1 smoke
gate (scripts/tier1.sh) imports :func:`run_chaos_round` and replays a
fixed seed range; the 200-iteration sweep is slow-marked.
"""

import random
import threading
import time

import numpy as np
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime import faults
from presto_tpu.runtime.errors import (
    DeviceOutOfMemory,
    PrestoError,
    ResourceExhausted,
    TransientFailure,
)
from presto_tpu.runtime.memory import MemoryPool, device_budget_bytes
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

SF = 0.005

#: small, deterministic, fully ORDER BY'd statements covering scans,
#: aggregation, hash join, and semi join — small build sides keep the
#: grouped-execution compiles cheap enough for the tier-1 smoke
CHAOS_QUERIES = {
    "scan": "select n_name from nation order by n_name",
    "agg": (
        "select l_returnflag f, l_linestatus s, count(*) c, "
        "sum(l_quantity) q from lineitem "
        "group by l_returnflag, l_linestatus order by f, s"
    ),
    "join": (
        "select n_name, count(*) c, sum(s_acctbal) b "
        "from supplier join nation on s_nationkey = n_nationkey "
        "group by n_name order by n_name"
    ),
    "semi": (
        "select count(*) c from customer where c_nationkey in "
        "(select n_nationkey from nation where n_regionkey = 1)"
    ),
}

#: armable sites: PR-1 hook points, the PR-4 jitted-step sites, and
#: the spill-tier transfer/re-partition sites (exec/spill.py)
FAULT_SITES = (
    "scan",
    "aggregation",
    "exchange",
    "step.join_build",
    "step.agg",
    "step.grouped_join",
    "step.spill_transfer",
    "step.spill_partition",
    "step.cancel_checkpoint",
)

#: generous wall bound per round — trips only on genuine hangs (cold
#: XLA compiles on a 1-core box legitimately take tens of seconds)
HANG_BUDGET_S = 300.0


def build_oracle(conn) -> dict:
    """Fault-free expected results, one clean session per query."""
    out = {}
    for name, q in CHAOS_QUERIES.items():
        out[name] = Session({"tpch": conn}).sql(q)
    return out


def frames_equal(got, want) -> bool:
    """Order-insensitive equality with float tolerance."""
    if list(got.columns) != list(want.columns) or len(got) != len(want):
        return False
    cols = list(want.columns)
    g = got.sort_values(cols, ignore_index=True)
    w = want.sort_values(cols, ignore_index=True)
    for c in cols:
        gv, wv = g[c], w[c]
        if np.issubdtype(np.asarray(wv).dtype, np.floating):
            if not np.allclose(np.asarray(gv, float), np.asarray(wv, float),
                               rtol=1e-6, equal_nan=True):
                return False
        elif gv.tolist() != wv.tolist():
            return False
    return True


def _arm_faults(inj: faults.FaultInjector, rng: random.Random) -> None:
    for _ in range(rng.randint(0, 3)):
        site = rng.choice(FAULT_SITES)
        times = rng.choice([1, 2, None])
        probability = rng.choice([1.0, 1.0, 0.5])
        if site.startswith("step."):
            inj.inject_oom(site, times=times, probability=probability)
        else:
            inj.inject(
                site,
                error=rng.choice(
                    [TransientFailure, faults.BackendOom, ResourceExhausted]
                ),
                times=times,
                probability=probability,
            )


def _assert_flight_postmortem(session, info) -> None:
    """The flight-recorder contract the chaos suite enforces on every
    typed failure (and every degraded success): exactly one COMPLETE
    post-mortem — plan render, spans (tracing is on in these rounds),
    attributed metric delta, rung history list — captured at the
    run_plan choke point, holding zero pool reservation."""
    recs = [r for r in session.flight.records()
            if r.query_id == info.query_id]
    assert len(recs) == 1, (
        f"{info.query_id}: {len(recs)} flight records (want exactly 1)"
    )
    rec = recs[0]
    assert rec.plan_render and "render failed" not in rec.plan_render
    assert rec.spans, "post-mortem captured no trace spans"
    assert rec.metrics, "post-mortem captured no metric delta"
    assert isinstance(rec.rung_history, list)
    assert rec.oom_rung == info.oom_retries
    # the history carries BOTH ladder rungs (runtime-OOM re-plans) and
    # planned out-of-core decisions — distinguishable by kind, and only
    # the former count as ladder rungs
    ladder = [e for e in rec.rung_history
              if e.get("kind", "ladder") == "ladder"]
    assert len(ladder) == info.oom_retries
    assert all(
        e["kind"] in ("planned_hybrid", "planned_grouped")
        for e in rec.rung_history if e not in ladder
    )
    # recording must never hold pool capacity: the reservation was
    # released BEFORE capture, and the record proves it
    assert rec.pool.get("reserved_bytes", 0) == 0
    # the export path is part of the contract: a record that cannot
    # round-trip through JSON is not a post-mortem anyone can read
    import json as _json

    dumped = _json.loads(session.export_flight_record(
        query_id=info.query_id))
    assert dumped["queryId"] == info.query_id
    assert dumped["planRender"] == rec.plan_render


def run_chaos_round(conn, oracle, seed: int, mesh=None) -> str:
    """One seeded round. Asserts the robustness contract and returns an
    outcome label ("ok:<query>", "typed:<ERROR_CODE>:<query>")."""
    from presto_tpu.runtime.errors import error_code

    rng = random.Random(seed)
    qname = rng.choice(sorted(CHAOS_QUERIES))
    props = {
        "retry_count": rng.choice([0, 1, 2]),
        "retry_backoff_s": 0.0,
        "query_retries": rng.choice([0, 0, 1]),
        "oom_ladder_max": rng.choice([0, 2, 4]),
        "result_cache_enabled": rng.random() < 0.5,
        "admission_queue_timeout_s": rng.choice([0.2, 30.0]),
    }
    if rng.random() < 0.35:
        # a tiny build budget routes joins/aggs through the planned
        # hybrid-spill tier, so the step.spill_transfer /
        # step.spill_partition fault sites actually execute mid-spill
        props["join_build_budget_bytes"] = rng.choice([64, 512, 4096])
    if rng.random() < 0.15:
        # a starved pool: admission must fail TYPED, never hang or leak
        props["memory_pool_bytes"] = rng.choice([1, 64])
    if rng.random() < 0.2:
        props["query_max_run_time"] = 120.0
    session = Session({"tpch": conn}, properties=props, mesh=mesh)
    inj = faults.FaultInjector(seed=seed)
    _arm_faults(inj, rng)
    t0 = time.monotonic()
    outcome = None
    try:
        with faults.injected(inj):
            df = session.sql(CHAOS_QUERIES[qname])
    except Exception as e:  # noqa: BLE001 — the contract under test
        assert isinstance(e, PrestoError), (
            f"seed {seed}: untyped failure {type(e).__name__}: {e}"
        )
        outcome = f"typed:{error_code(e)}:{qname}"
        # flight-recorder contract: the surfaced failure's attempt left
        # exactly one complete, JSON-exportable post-mortem
        failed = [i for i in session.query_history if i.state == "FAILED"]
        assert failed, f"seed {seed}: typed failure but no FAILED info"
        _assert_flight_postmortem(session, failed[-1])
    else:
        assert frames_equal(df, oracle[qname]), (
            f"seed {seed}: WRONG ANSWER on {qname} "
            f"(faults: {[s.site for s in inj.specs]})"
        )
        outcome = f"ok:{qname}"
        info = session.query_history[-1]
        if info.oom_retries > 0 or info.fragment_retries > 0:
            # degraded/retried successes auto-capture too (rung > 0 is
            # evidence worth keeping even when the answer was right)
            _assert_flight_postmortem(session, info)
    wall = time.monotonic() - t0
    assert wall < HANG_BUDGET_S, f"seed {seed}: round took {wall:.0f}s"
    assert session.pool().reserved_bytes == 0, (
        f"seed {seed}: memory pool reservation leak"
    )
    assert session.pool().queued_count == 0
    return outcome


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=SF)


@pytest.fixture(scope="module")
def oracle(conn):
    return build_oracle(conn)


def _counter(name):
    return REGISTRY.snapshot().get(name, 0.0)


# ---------------------------------------------------------------------------
# the degradation ladder (the ISSUE-4 acceptance shape: a build-side
# estimate forced wrong completes correctly where it used to die)
# ---------------------------------------------------------------------------


class _DegradeRecorder:
    def __init__(self):
        self.rungs = []

    def query_degraded(self, info):
        self.rungs.append(info.oom_retries)


def test_ladder_recovers_from_runtime_oom(conn, oracle):
    """The in-memory join build OOMs on EVERY attempt (the stats said
    it fits — they were wrong); the ladder re-plans onto grouped
    execution, which dispatches at a different site and completes
    correctly."""
    s = Session({"tpch": conn})
    rec = _DegradeRecorder()
    s.add_event_listener(rec)
    before = _counter("query.oom_degraded")
    inj = faults.FaultInjector()
    inj.inject_oom("step.join_build", times=None)
    with faults.injected(inj):
        df = s.sql(CHAOS_QUERIES["join"])
    assert frames_equal(df, oracle["join"])
    info = s.query_history[-1]
    assert info.state == "FINISHED"
    assert info.oom_retries == 1
    assert rec.rungs == [1]  # fragment_retried-style event per rung
    assert _counter("query.oom_degraded") == before + 1
    assert inj.fired_at("step.join_build") == 1
    assert inj.fired_at("step.grouped_join") == 0


def test_ladder_second_rung_doubles_buckets(conn, oracle):
    """Rung 1's grouped pass ALSO OOMs once: rung 2 re-plans with
    doubled buckets / halved probe chunks and completes."""
    s = Session({"tpch": conn})
    inj = faults.FaultInjector()
    inj.inject_oom("step.join_build", times=None)
    inj.inject_oom("step.grouped_join", times=1)
    with faults.injected(inj):
        df = s.sql(CHAOS_QUERIES["join"])
    assert frames_equal(df, oracle["join"])
    assert s.query_history[-1].oom_retries == 2


def test_ladder_disabled_raises_typed_oom(conn):
    s = Session({"tpch": conn}, properties={"oom_ladder_max": 0})
    inj = faults.FaultInjector()
    inj.inject_oom("step.join_build", times=None)
    with faults.injected(inj):
        with pytest.raises(DeviceOutOfMemory):
            s.sql(CHAOS_QUERIES["join"])
    info = s.query_history[-1]
    assert info.state == "FAILED"
    assert info.error_code == "DEVICE_OUT_OF_MEMORY"
    assert info.oom_retries == 0
    assert s.pool().reserved_bytes == 0


def test_ladder_exhaustion_is_typed_not_a_loop(conn):
    """Every rung OOMs (grouped included): the ladder must stop at
    oom_ladder_max with the typed error, not spin."""
    s = Session({"tpch": conn}, properties={"oom_ladder_max": 2})
    inj = faults.FaultInjector()
    inj.inject_oom("step", times=None, per_site=False)
    with faults.injected(inj):
        with pytest.raises(DeviceOutOfMemory):
            s.sql(CHAOS_QUERIES["join"])
    assert s.query_history[-1].oom_retries == 2  # both rungs were tried
    assert s.pool().reserved_bytes == 0


def test_oom_at_aggregation_step_recovers(conn, oracle):
    """Local aggregations have no spill tier to re-plan onto (they are
    already morsel-bounded), so a ladder rung here is a plain re-run —
    which recovers this transient (times=1) OOM."""
    s = Session({"tpch": conn})
    inj = faults.FaultInjector()
    inj.inject_oom("step.agg", times=1)
    with faults.injected(inj):
        df = s.sql(CHAOS_QUERIES["agg"])
    assert frames_equal(df, oracle["agg"])
    assert s.query_history[-1].oom_retries == 1


def test_degraded_local_run_gets_its_own_ladder(conn, oracle):
    """Distributed exchange faults force degradation to the local
    pipeline, whose in-memory join build ALSO OOMs (one device holds
    mesh-size times the data): the degraded run must walk its own
    ladder onto grouped execution — the two ladders' rungs add up on
    the QueryInfo."""
    from presto_tpu.parallel.mesh import make_mesh

    # int group key -> sort strategy -> the exchange path (a dictionary
    # key would take the direct psum path and never hit the fault site).
    # min(n_regionkey) keeps a build-side OUTPUT on the join: without
    # one, the leaf-route framework (ISSUE-9) folds the filter-only
    # unique join into a membership bitmap and the faulted
    # join-build/exchange sites this test is about never execute
    q = ("select s_nationkey k, count(*) c, min(n_regionkey) r "
         "from supplier join nation "
         "on s_nationkey = n_nationkey group by s_nationkey order by k")
    want = Session({"tpch": conn}).sql(q)
    s = Session({"tpch": conn}, mesh=make_mesh(2),
                properties={"retry_count": 0, "retry_backoff_s": 0.0})
    inj = faults.FaultInjector()
    inj.inject("exchange.aggregate", times=None)  # the mesh never works
    inj.inject_oom("step.join_build", times=None)  # in-memory ALWAYS OOMs
    with faults.injected(inj):
        df = s.sql(q)
    assert frames_equal(df, want)
    info = s.query_history[-1]
    assert info.state == "FINISHED"
    assert info.degraded  # distributed tier abandoned
    # one rung on the distributed attempt, one on the degraded local run
    assert info.oom_retries == 2
    assert s.pool().reserved_bytes == 0


def test_oom_surfaces_in_query_history_table(conn):
    s = Session({"tpch": conn})
    inj = faults.FaultInjector()
    inj.inject_oom("step.join_build", times=None)
    with faults.injected(inj):
        s.sql(CHAOS_QUERIES["join"])
    h = s.sql(
        "select oom_retries, memory_queued_s from query_history "
        "where oom_retries > 0"
    )
    assert len(h) >= 1 and int(h["oom_retries"].max()) >= 1
    p = s.sql("select * from memory_pool")
    assert len(p) == 1
    # the history scan itself holds the only live reservation
    assert int(p["capacity_bytes"][0]) > 0
    assert int(p["active_queries"][0]) <= 1


# ---------------------------------------------------------------------------
# seeded chaos sweeps
# ---------------------------------------------------------------------------


def test_chaos_smoke_seeded(conn, oracle):
    """A fixed-seed slice of the chaos space on every tier-1 run (the
    same seeds 0..9 scripts/tier1.sh replays)."""
    outcomes = [run_chaos_round(conn, oracle, seed) for seed in range(10)]
    assert len(outcomes) == 10
    assert any(o.startswith("ok:") for o in outcomes)


@pytest.mark.slow
def test_chaos_200_rounds(conn, oracle):
    """ISSUE-4 acceptance: 200 seeded rounds, zero wrong answers, zero
    hangs, zero reservation leaks (each round asserts its own
    invariants; this sweep proves breadth)."""
    outcomes = [run_chaos_round(conn, oracle, seed) for seed in range(200)]
    ok = sum(o.startswith("ok:") for o in outcomes)
    typed = sum(o.startswith("typed:") for o in outcomes)
    assert ok + typed == 200
    # the schedule space must actually exercise both halves of the
    # contract, or the sweep proves nothing
    assert ok >= 20 and typed >= 20, (ok, typed)


@pytest.mark.slow
def test_chaos_distributed_rounds(conn):
    """Chaos over the virtual 8-device mesh: exchange faults, OOM
    ladder, and distributed->local degradation all in play."""
    from presto_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    oracle = build_oracle(conn)
    outcomes = [
        run_chaos_round(conn, oracle, seed, mesh=mesh)
        for seed in range(12)
    ]
    assert len(outcomes) == 12


def test_chaos_mixed_ingest_subscriptions(conn, oracle):
    """ISSUE-17 acceptance: concurrent micro-batch appends + continuous
    subscriptions + ad-hoc queries under injected faults. The gates:
    zero stale deliveries (every result >= its fire-epoch row floor),
    same-template subscriptions demonstrably batch (mean gate batch
    size > 1), an approx-mode subscription returns a flagged
    superset-of-exact semi join, per-tenant fairness admits everyone,
    p99 refresh stays bounded, and pool + host-spill budgets drain."""
    import pandas as pd

    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.runtime.memory import global_host_spill_budget
    from presto_tpu.server.frontend import QueryServer
    from presto_tpu.stream import StreamWriter

    mconn = MemoryConnector()
    s = Session(
        {"memory": mconn, "tpch": conn},
        properties={
            "batched_dispatch": True,
            "result_cache_enabled": True,
            "retry_count": 2,
            "retry_backoff_s": 0.0,
        },
    )
    server = QueryServer(session=s)
    w = StreamWriter(s)
    rng0 = np.random.default_rng(1717)

    def ticks(n, lo=0):
        return pd.DataFrame({
            "k": np.arange(lo, lo + n, dtype=np.int64),
            "v": (np.arange(lo, lo + n, dtype=np.int64) * 3) % 100,
        })

    rows_at_epoch = {}
    # big enough that a warm refresh does real work (scan + sort over
    # ~100k rows): concurrent same-template refreshes OVERLAP, so they
    # actually meet at the gate instead of finishing between thread
    # spawns — the dashboard load shape the batcher exists for
    r0 = w.append("ticks", ticks(100_000))
    rows_at_epoch[r0.epoch] = r0.total_rows

    # the approx tier's semi-join shape: build keys over ~1e12, so the
    # exact exists-bitmap can't admit the domain and the Bloom sketch
    # carries the probe (superset-of-exact, flagged)
    ckeys = rng0.integers(0, 1_000_000_000_000, 400).astype(np.int64)
    w.append("orders", pd.DataFrame({
        "okey": np.arange(3000, dtype=np.int64),
        "ckey": np.concatenate([
            rng0.choice(ckeys, 2200),
            rng0.integers(0, 1_000_000_000_000, 800),
        ]).astype(np.int64),
    }))
    w.append("cust", pd.DataFrame({
        "ckey": ckeys, "grp": rng0.integers(0, 5, 400).astype(np.int64),
    }))
    semi_sql = ("select count(*) n from orders where ckey in "
                "(select ckey from cust where grp = 2)")
    semi_exact = int(server.execute(semi_sql, "adhoc")["n"][0])

    #: one template, distinct literals, every literal ABOVE the value
    #: range — each refresh returns ALL rows, so len(df) is directly
    #: comparable to the fire-epoch row floor (zero-stale oracle)
    fmt = "select k, v from ticks where v < {} order by k limit 1000000"
    lits = (150, 175, 200, 225, 250)
    subs = [server.subscribe(fmt.format(lit), f"dash-{i % 3}")
            for i, lit in enumerate(lits)]
    approx_sub = server.subscribe(semi_sql, "dash-approx", mode="approx")

    d0 = _counter("batch.dispatched")
    q0 = _counter("batch.queries")
    stale0 = _counter("subscription.stale_blocked")
    inj = faults.FaultInjector(seed=1717)
    # bounded schedules: the round must eventually run clean so every
    # waiter converges — unbounded scan failure would FAIL the subs
    inj.inject("scan", error=TransientFailure, times=8, probability=0.5)
    inj.inject_oom("step.agg", times=2)
    inj.inject_oom("step.join_build", times=2)

    untyped, wrong = [], []
    t0 = time.monotonic()

    def adhoc(wid):
        rng = random.Random(500 + wid)
        for _ in range(4):
            qname = rng.choice(sorted(CHAOS_QUERIES))
            try:
                df = server.execute(CHAOS_QUERIES[qname], "adhoc")
            except Exception as e:  # noqa: BLE001 — the contract under test
                if not isinstance(e, PrestoError):
                    untyped.append(f"adhoc{wid}: {type(e).__name__}: {e}")
            else:
                if not frames_equal(df, oracle[qname]):
                    wrong.append(f"adhoc{wid}: {qname}")

    def writer():
        for i in range(8):
            r = w.append("ticks", ticks(4000, lo=1_000_000 * (i + 1)))
            rows_at_epoch[r.epoch] = r.total_rows
            time.sleep(0.12)

    threads = [threading.Thread(target=writer, daemon=True)] + [
        threading.Thread(target=adhoc, args=(i,), daemon=True)
        for i in range(2)
    ]
    with faults.injected(inj):
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=HANG_BUDGET_S)
            assert not t.is_alive(), "mixed-load worker hung"
        # let every sub converge on the chaotic phase's last epoch
        mid_epoch = mconn.table_epoch("ticks")
        for sub in subs:
            sub.wait_for_epoch("ticks", mid_epoch, timeout_s=HANG_BUDGET_S)
    # land ONE more append with all five subs idle AND the injector
    # uninstalled — a synchronized burst, the load shape where
    # same-template refreshes meet at the gate. (An active injector
    # disables coalescing/batching by design — the same admission rule
    # as the result cache, lifecycle.InflightCoalescer — so fused
    # dispatch can only be demonstrated outside the faulted window.)
    rf = w.append("ticks", ticks(4000, lo=9_000_000))
    rows_at_epoch[rf.epoch] = rf.total_rows
    final_epoch = rf.epoch
    got_final = [sub.wait_for_epoch("ticks", final_epoch,
                                    timeout_s=HANG_BUDGET_S)
                 for sub in subs]
    # bump the approx sub's build side for one CLEAN refresh: a fire
    # that ate an injected join-build OOM mid-round correctly degrades
    # to the exact spill join (flagged exact, the conservative answer),
    # so the sketch contract is asserted on a post-fault fire
    ra = w.append("orders", pd.DataFrame({
        "okey": np.arange(3000, 3050, dtype=np.int64),
        "ckey": rng0.choice(ckeys, 50).astype(np.int64),
    }))
    approx_res = approx_sub.wait_for_epoch("orders", ra.epoch,
                                           timeout_s=HANG_BUDGET_S)
    semi_exact = int(server.execute(semi_sql, "adhoc")["n"][0])
    try:
        assert untyped == [] and wrong == []
        # zero stale: every delivered frame carries at least the rows
        # that existed at its fire epoch (appends only grow the table)
        for sub in subs:
            assert sub.state == "ACTIVE", sub.last_error
            for res in sub.results():
                floor = rows_at_epoch.get(res.epochs.get("ticks"))
                assert floor is not None
                assert len(res.df) >= floor, (
                    f"STALE: {len(res.df)} rows delivered at epoch "
                    f"{res.epochs['ticks']} (floor {floor})")
        for res in got_final:  # the converged view is exactly current
            assert len(res.df) == rows_at_epoch[final_epoch]
        assert _counter("subscription.stale_blocked") == stale0
        # same-template refreshes met at the gate and fused
        dd = _counter("batch.dispatched") - d0
        qd = _counter("batch.queries") - q0
        assert dd >= 1, "no batched dispatch under mixed load"
        assert qd / dd > 1.0, f"mean gate batch size {qd}/{dd} <= 1"
        # the approx tier: flagged, superset of exact, never silent
        assert approx_res.approximate
        assert int(approx_res.df["n"][0]) >= semi_exact
        # fairness: every tenant class was admitted during the round
        # (metric suffixes are OpenMetrics-sanitized: "-" becomes "_")
        for tname in ("dash-0", "dash-1", "dash-2", "dash-approx", "adhoc"):
            mname = tname.replace("-", "_")
            assert _counter(f"tenant.admitted.{mname}") > 0, tname
        # bounded refresh latency (trips only on genuine hangs)
        p99 = REGISTRY.histogram("subscription.refresh_s").quantile(0.99)
        assert 0 < p99 < HANG_BUDGET_S
        assert time.monotonic() - t0 < HANG_BUDGET_S
    finally:
        server.shutdown()
    # budgets drained: no reservation outlives the round
    assert s.pool().reserved_bytes == 0 and s.pool().queued_count == 0
    assert global_host_spill_budget().reserved_bytes == 0


@pytest.mark.slow
def test_chaos_concurrent_sessions_shared_pool(conn, oracle):
    """Concurrent sessions + a pool sized for roughly one query at a
    time + injected faults: every thread's queries are correct or
    typed, nobody hangs, and the shared pool drains to zero."""
    probe = Session({"tpch": conn})
    probe.sql(CHAOS_QUERIES["agg"])
    peak = max(
        probe.query_history[-1].memory_reserved_bytes,
        device_budget_bytes() // (1 << 12),
    )
    pool = MemoryPool(int(peak * 2), name="chaos")
    inj = faults.FaultInjector(seed=99)
    inj.inject("scan", times=4)
    inj.inject_oom("step.join_build", times=2)
    failures = []

    def worker(wid: int):
        rng = random.Random(1000 + wid)
        try:
            s = Session(
                {"tpch": conn}, memory_pool=pool,
                properties={
                    "retry_count": 2,
                    "retry_backoff_s": 0.0,
                    "admission_queue_timeout_s": 120.0,
                },
            )
            for _ in range(3):
                qname = rng.choice(sorted(CHAOS_QUERIES))
                try:
                    df = s.sql(CHAOS_QUERIES[qname])
                except Exception as e:  # noqa: BLE001
                    if not isinstance(e, PrestoError):
                        failures.append(f"w{wid}: untyped {type(e).__name__}")
                else:
                    if not frames_equal(df, oracle[qname]):
                        failures.append(f"w{wid}: wrong answer on {qname}")
        except Exception as e:  # noqa: BLE001
            failures.append(f"w{wid}: harness {type(e).__name__}: {e}")

    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(3)
    ]
    with faults.injected(inj):
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=HANG_BUDGET_S)
            assert not t.is_alive(), "worker hung"
    assert failures == []
    assert pool.reserved_bytes == 0 and pool.queued_count == 0


def test_chaos_overload_storm_seeded(conn, oracle):
    """ISSUE-19 storm round: a burst 4x over slot capacity against the
    serving tier, mid-run cancels, and seeded faults that include the
    new ``step.cancel_checkpoint`` site. The closed-loop contract:
    zero untyped failures anywhere — every submission either FINISHES
    with the oracle answer, FAILS with a typed code, or is shed at
    accept time with the typed retryable ``ServerOverloaded`` (each
    shed counted under ``overload.shed``) — and every budget (memory
    pool, host-spill, scheduler queue) drains to zero."""
    from presto_tpu.runtime.errors import ServerOverloaded
    from presto_tpu.runtime.memory import global_host_spill_budget
    from presto_tpu.server.frontend import QueryServer

    rng = random.Random(1906)
    srv = QueryServer(
        {"tpch": conn}, total_slots=2,
        shed_queue_limit=4, shed_tenant_queue_limit=3,
        properties={
            "health_monitor": False,
            "result_cache_enabled": False,
            "retry_backoff_s": 0.0,
        },
    )
    inj = faults.FaultInjector(seed=1906)
    # the checkpoint site itself is stormed: a backend-shaped OOM at a
    # cancel checkpoint must surface as the typed DeviceOutOfMemory
    # (or be absorbed by the ladder), never as an untyped RuntimeError
    inj.inject_oom("step.cancel_checkpoint", times=2, probability=0.5)
    inj.inject("scan", error=TransientFailure, times=2, probability=0.5)
    shed0 = _counter("overload.shed")
    cancel0 = _counter("server.cancel_requests")
    submitted, shed, cancelled = [], 0, []
    # pin both slots during the burst so the queue builds
    # deterministically past the shed ceilings (4x over capacity)
    holds = [srv.scheduler.acquire("burst"), srv.scheduler.acquire("burst")]
    try:
        with faults.injected(inj):
            for i in range(8):
                qname = rng.choice(sorted(CHAOS_QUERIES))
                tenant = rng.choice(["burst", "burst", "walkin"])
                try:
                    qid = srv.submit(CHAOS_QUERIES[qname], tenant=tenant)
                except ServerOverloaded as e:
                    shed += 1
                    assert e.retryable and e.retry_after_s > 0
                else:
                    submitted.append((qid, qname))
                    # admitted workers enqueue asynchronously; let each
                    # reach the fair queue so the ceilings see the true
                    # depth (the storm is about backlog, not racing the
                    # thread scheduler)
                    t0 = time.monotonic()
                    while (srv.scheduler.queue_depth() < len(submitted)
                           and time.monotonic() - t0 < 10.0):
                        time.sleep(0.002)
            # mid-run cancels: a sample of the burst dies on purpose
            for qid, _ in rng.sample(submitted,
                                     max(1, len(submitted) // 3)):
                out = srv.cancel(qid, reason="storm cancel")
                assert out["cancelled"] is True
                cancelled.append(qid)
            for h in holds:
                srv.scheduler.release(h)
            holds = []
            for qid, _ in submitted:
                assert srv._queries[qid]["done"].wait(HANG_BUDGET_S), (
                    f"{qid} hung in the storm")
        for qid, qname in submitted:
            page = srv.poll(qid)
            if page["state"] == "FINISHED":
                assert frames_equal(srv._queries[qid]["df"],
                                    oracle[qname]), (
                    f"{qid}: WRONG ANSWER on {qname} under storm")
            else:
                assert page["state"] == "FAILED"
                assert page["errorCode"] and page["errorCode"] != "INTERNAL", (
                    f"{qid}: untyped failure {page.get('error')}")
        cancelled_pages = [srv.poll(q) for q in cancelled]
        assert any(p["state"] == "FAILED"
                   and p["errorCode"] == "QUERY_CANCELLED"
                   for p in cancelled_pages), (
            "no mid-run cancel was observed as QUERY_CANCELLED")
    finally:
        for h in holds:
            srv.scheduler.release(h)
        srv.shutdown()
    assert shed >= 1, "a 4x burst never tripped the shed ceilings"
    assert _counter("overload.shed") - shed0 >= shed
    assert _counter("server.cancel_requests") - cancel0 == len(cancelled)
    # budgets drained: nothing outlives the storm
    assert srv.session.pool().reserved_bytes == 0
    assert srv.session.pool().queued_count == 0
    assert global_host_spill_budget().reserved_bytes == 0
    assert srv.scheduler.queue_depth() == 0
