"""Distributed execution tests on the virtual 8-device CPU mesh.

Reference parity: DistributedQueryRunner — coordinator + N workers in
one process with *real* exchanges [SURVEY §4]. Here the workers are
mesh devices and the exchanges are real all_to_all / all_gather
collectives; metamorphic invariant: results are independent of mesh
shape and of the broadcast-vs-repartition join distribution choice.
"""

import jax
import numpy as np
import pandas as pd
import pytest

from presto_tpu.batch import Batch
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch.queries import QUERIES
from presto_tpu.oracle.tpch_oracle import ORACLES
from presto_tpu.ops.hashing import partition_ids
from presto_tpu.parallel.exchange import make_broadcast_step, make_shuffle_step
from presto_tpu.parallel.mesh import make_mesh, row_sharding
from presto_tpu.runtime.session import Session
from presto_tpu.types import BIGINT, DOUBLE

from tests.test_tpch_sql import compare

SF = 0.005


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


@pytest.fixture(scope="module")
def env(mesh):
    conn = TpchConnector(sf=SF, units_per_split=1 << 14)
    session = Session({"tpch": conn}, mesh=mesh)
    tables = {name: conn.table_pandas(name) for name in conn.tables()}
    return session, tables


# ---------------------------------------------------------------------------
# exchange primitives
# ---------------------------------------------------------------------------


def _random_batch(rng, cap):
    k = rng.integers(0, 1000, cap, dtype=np.int64)
    v = rng.normal(size=cap)
    return Batch.from_numpy(
        {"k": k, "v": v}, {"k": BIGINT, "v": DOUBLE}, count=cap - 17
    )


def test_shuffle_roundtrip_preserves_rows(mesh, rng):
    n = 8
    b = _random_batch(rng, 8 * 512)
    sharded = jax.device_put(b, row_sharding(mesh))
    pids = jax.device_put(
        partition_ids([sharded["k"].data], n), row_sharding(mesh)
    )
    step = make_shuffle_step(mesh, n, quota=256)
    out, overflow = step(sharded, pids)
    assert not bool(overflow)
    # multiset of live (k, v) rows is preserved
    live_in = np.asarray(b.live)
    live_out = np.asarray(out.live)
    got = sorted(
        zip(
            np.asarray(out["k"].data)[live_out].tolist(),
            np.round(np.asarray(out["v"].data)[live_out], 9).tolist(),
        )
    )
    want = sorted(
        zip(
            np.asarray(b["k"].data)[live_in].tolist(),
            np.round(np.asarray(b["v"].data)[live_in], 9).tolist(),
        )
    )
    assert got == want
    # every row landed on the device that owns its hash partition
    kk = np.asarray(out["k"].data)
    owner = np.asarray(partition_ids([jax.numpy.asarray(kk)], n))
    rows_per_dev = out.capacity // n
    dev_of_row = np.arange(out.capacity) // rows_per_dev
    assert (owner[live_out] == dev_of_row[live_out]).all()


def test_shuffle_overflow_flag(mesh, rng):
    n = 8
    b = _random_batch(rng, 8 * 512)
    sharded = jax.device_put(b, row_sharding(mesh))
    # everything to partition 0 with a tiny quota -> must overflow
    zeros = jax.device_put(
        jax.numpy.zeros(8 * 512, jax.numpy.int32), row_sharding(mesh)
    )
    step = make_shuffle_step(mesh, n, quota=16)
    _, overflow = step(sharded, zeros)
    assert bool(overflow)


def test_broadcast_replicates_all_rows(mesh, rng):
    b = _random_batch(rng, 8 * 64)
    sharded = jax.device_put(b, row_sharding(mesh))
    out = make_broadcast_step(mesh)(sharded)
    assert out.capacity == 8 * 64  # every device holds the full row set
    live_in = np.asarray(b.live)
    live_out = np.asarray(out.live)
    assert sorted(np.asarray(out["k"].data)[live_out].tolist()) == sorted(
        np.asarray(b["k"].data)[live_in].tolist()
    )


# ---------------------------------------------------------------------------
# full TPC-H over the mesh (engine vs oracle)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(QUERIES, key=lambda x: int(x[1:])))
def test_tpch_distributed_matches_oracle(env, name):
    session, tables = env
    got = session.sql(QUERIES[name])
    want = ORACLES[name](tables)
    compare(got, want, name)


# ---------------------------------------------------------------------------
# metamorphic invariants (SURVEY §7.4 #8)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_devices", [1, 2, 4])
def test_result_independent_of_mesh_shape(n_devices):
    conn = TpchConnector(sf=SF, units_per_split=1 << 14)
    local = Session({"tpch": conn}).sql(QUERIES["q3"])
    dist = Session({"tpch": conn}, mesh=make_mesh(n_devices)).sql(QUERIES["q3"])
    pd.testing.assert_frame_equal(
        local.reset_index(drop=True), dist.reset_index(drop=True),
        check_dtype=False, atol=1e-6,
    )


@pytest.mark.parametrize("name", ["q3", "q10", "q13", "q16", "q21"])
def test_repartition_join_path(mesh, name):
    """broadcast_join_row_limit=0 forces the all_to_all join path for
    every join — the FIXED_HASH distribution must agree with the
    broadcast plan and the oracle."""
    conn = TpchConnector(sf=SF, units_per_split=1 << 14)
    session = Session(
        {"tpch": conn}, properties={"broadcast_join_row_limit": 0}, mesh=mesh
    )
    got = session.sql(QUERIES[name])
    tables = {t: conn.table_pandas(t) for t in conn.tables()}
    want = ORACLES[name](tables)
    compare(got, want, name)


def test_gather_fallback_guard(mesh):
    """The replicate-everything window/sort fallbacks must fail fast
    with a clear error above gather_row_limit instead of silently
    multiplying memory by the mesh size (round-1 advisor finding)."""
    import pytest

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.exec.operators import CapacityOverflow
    from presto_tpu.runtime.session import Session

    s = Session(
        {"tpch": TpchConnector(sf=0.01)},
        properties={"gather_row_limit": 16},
        mesh=mesh,
    )
    with pytest.raises(CapacityOverflow, match="gather_limit"):
        s.sql("select l_orderkey from lineitem order by l_orderkey")
    # small inputs still pass through the fallback (region: 5 rows < 16)
    df = s.sql("select r_name from region order by r_name limit 3")
    assert len(df) == 3
