"""Distributed execution tests on the virtual 8-device CPU mesh.

Reference parity: DistributedQueryRunner — coordinator + N workers in
one process with *real* exchanges [SURVEY §4]. Here the workers are
mesh devices and the exchanges are real all_to_all / all_gather
collectives; metamorphic invariant: results are independent of mesh
shape and of the broadcast-vs-repartition join distribution choice.
"""

import jax
import numpy as np
import pandas as pd
import pytest

pytestmark = pytest.mark.slow

from presto_tpu.batch import Batch
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch.queries import QUERIES
from presto_tpu.oracle.tpch_oracle import ORACLES
from presto_tpu.ops.hashing import partition_ids
from presto_tpu.parallel.exchange import make_broadcast_step, make_shuffle_step
from presto_tpu.parallel.mesh import make_mesh, row_sharding
from presto_tpu.runtime.session import Session
from presto_tpu.types import BIGINT, DOUBLE

from tests.test_tpch_sql import compare

SF = 0.005


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


@pytest.fixture(scope="module")
def env(mesh):
    conn = TpchConnector(sf=SF, units_per_split=1 << 14)
    session = Session({"tpch": conn}, mesh=mesh)
    tables = {name: conn.table_pandas(name) for name in conn.tables()}
    return session, tables


# ---------------------------------------------------------------------------
# exchange primitives
# ---------------------------------------------------------------------------


def _random_batch(rng, cap):
    k = rng.integers(0, 1000, cap, dtype=np.int64)
    v = rng.normal(size=cap)
    return Batch.from_numpy(
        {"k": k, "v": v}, {"k": BIGINT, "v": DOUBLE}, count=cap - 17
    )


def test_shuffle_roundtrip_preserves_rows(mesh, rng):
    n = 8
    b = _random_batch(rng, 8 * 512)
    sharded = jax.device_put(b, row_sharding(mesh))
    pids = jax.device_put(
        partition_ids([sharded["k"].data], n), row_sharding(mesh)
    )
    step = make_shuffle_step(mesh, n, quota=256)
    out, overflow = step(sharded, pids)
    assert not bool(overflow)
    # multiset of live (k, v) rows is preserved
    live_in = np.asarray(b.live)
    live_out = np.asarray(out.live)
    got = sorted(
        zip(
            np.asarray(out["k"].data)[live_out].tolist(),
            np.round(np.asarray(out["v"].data)[live_out], 9).tolist(),
        )
    )
    want = sorted(
        zip(
            np.asarray(b["k"].data)[live_in].tolist(),
            np.round(np.asarray(b["v"].data)[live_in], 9).tolist(),
        )
    )
    assert got == want
    # every row landed on the device that owns its hash partition
    kk = np.asarray(out["k"].data)
    owner = np.asarray(partition_ids([jax.numpy.asarray(kk)], n))
    rows_per_dev = out.capacity // n
    dev_of_row = np.arange(out.capacity) // rows_per_dev
    assert (owner[live_out] == dev_of_row[live_out]).all()


def test_shuffle_overflow_flag(mesh, rng):
    n = 8
    b = _random_batch(rng, 8 * 512)
    sharded = jax.device_put(b, row_sharding(mesh))
    # everything to partition 0 with a tiny quota -> must overflow
    zeros = jax.device_put(
        jax.numpy.zeros(8 * 512, jax.numpy.int32), row_sharding(mesh)
    )
    step = make_shuffle_step(mesh, n, quota=16)
    _, overflow = step(sharded, zeros)
    assert bool(overflow)


def test_multiround_shuffle_drains_zipfian_skew(mesh, rng):
    """Skew-aware exchange (SURVEY §7.4 #4): a zipfian key stream —
    one hot destination — completes at a small FIXED wire quota via
    extra rounds, where the single-round exchange would overflow and
    force a host-side quota doubling + recompile."""
    from presto_tpu.parallel.exchange import make_multiround_shuffle_step

    n = 8
    cap = 8 * 512
    # zipf-ish: ~70% of rows share one hot key -> one hot partition
    hot = rng.random(cap) < 0.7
    k = np.where(hot, 7, rng.integers(0, 1000, cap)).astype(np.int64)
    v = rng.normal(size=cap)
    from presto_tpu.batch import Batch as B

    b = B.from_numpy({"k": k, "v": v}, {"k": BIGINT, "v": DOUBLE}, count=cap - 9)
    sharded = jax.device_put(b, row_sharding(mesh))
    pids = jax.device_put(
        partition_ids([sharded["k"].data], n), row_sharding(mesh)
    )
    # wire quota 64 rows/dest/round: the hot device receives ~2850 rows
    # (>> 8*64 per round) yet the step completes without overflow
    step = make_multiround_shuffle_step(mesh, n, quota=64, recv_cap=4096)
    out, overflow = step(sharded, pids)
    assert not bool(overflow)
    live_in, live_out = np.asarray(b.live), np.asarray(out.live)
    got = sorted(
        zip(
            np.asarray(out["k"].data)[live_out].tolist(),
            np.round(np.asarray(out["v"].data)[live_out], 9).tolist(),
        )
    )
    want = sorted(
        zip(
            np.asarray(b["k"].data)[live_in].tolist(),
            np.round(np.asarray(b["v"].data)[live_in], 9).tolist(),
        )
    )
    assert got == want
    # rows landed on their hash owners
    kk = np.asarray(out["k"].data)
    owner = np.asarray(partition_ids([jax.numpy.asarray(kk)], n))
    dev_of_row = np.arange(out.capacity) // (out.capacity // n)
    assert (owner[live_out] == dev_of_row[live_out]).all()


def test_multiround_shuffle_receive_overflow_flag(mesh, rng):
    """Overflow now means true placement skew: a device owning more
    rows than recv_cap trips the flag (host doubles recv capacity)."""
    from presto_tpu.parallel.exchange import make_multiround_shuffle_step

    n = 8
    b = _random_batch(rng, 8 * 512)
    sharded = jax.device_put(b, row_sharding(mesh))
    zeros = jax.device_put(
        jax.numpy.zeros(8 * 512, jax.numpy.int32), row_sharding(mesh)
    )
    step = make_multiround_shuffle_step(mesh, n, quota=64, recv_cap=256)
    _, overflow = step(sharded, zeros)
    assert bool(overflow)


def test_broadcast_replicates_all_rows(mesh, rng):
    b = _random_batch(rng, 8 * 64)
    sharded = jax.device_put(b, row_sharding(mesh))
    out = make_broadcast_step(mesh)(sharded)
    assert out.capacity == 8 * 64  # every device holds the full row set
    live_in = np.asarray(b.live)
    live_out = np.asarray(out.live)
    assert sorted(np.asarray(out["k"].data)[live_out].tolist()) == sorted(
        np.asarray(b["k"].data)[live_in].tolist()
    )


# ---------------------------------------------------------------------------
# multi-host DCN mesh (2-D dcn/ici axes; SURVEY §2.5 DCN row)
# ---------------------------------------------------------------------------


def test_dcn_mesh_queries_match_flat_mesh():
    """Metamorphic: results are independent of mesh shape — the same
    queries over a 2-D ("dcn", "ici") mesh (the multi-host layout,
    here 2 virtual hosts x 4 devices) must equal the flat 8-worker
    mesh. Exercises the combined-axes all_to_all/all_gather/psum paths
    end to end: sharded scan, partial->shuffle->final aggregation,
    repartition + broadcast joins, range-partition sort."""
    from presto_tpu.parallel.mesh import make_dcn_mesh

    conn = TpchConnector(sf=0.005, units_per_split=1 << 14)
    flat = Session({"tpch": conn}, mesh=make_mesh(8))
    dcn = Session({"tpch": conn}, mesh=make_dcn_mesh(2, 4),
                  properties={"broadcast_join_row_limit": 0})
    queries = [
        # grouped agg through the multiround exchange
        "select l_suppkey, sum(l_quantity) q, count(*) c from lineitem "
        "group by l_suppkey order by l_suppkey",
        # repartition join (broadcast disabled on the dcn session)
        "select o_orderpriority, count(*) c from orders, lineitem "
        "where l_orderkey = o_orderkey and l_shipdate > date '1995-01-01' "
        "group by o_orderpriority order by o_orderpriority",
        # range-partition sort + topN
        "select l_orderkey, l_extendedprice from lineitem "
        "order by l_extendedprice desc, l_orderkey limit 20",
    ]
    for q in queries:
        a = flat.sql(q)
        b = dcn.sql(q)
        pd.testing.assert_frame_equal(
            a.reset_index(drop=True), b.reset_index(drop=True),
            check_dtype=False,
        )
    # broadcast-join path (default broadcast limit: the small build
    # side all_gathers over the combined axes, incl. _compact_step)
    dcn_bc = Session({"tpch": conn}, mesh=make_dcn_mesh(2, 4))
    q = ("select n_name, count(*) c from nation, customer "
         "where c_nationkey = n_nationkey group by n_name order by n_name")
    pd.testing.assert_frame_equal(
        flat.sql(q).reset_index(drop=True),
        dcn_bc.sql(q).reset_index(drop=True),
        check_dtype=False,
    )


def test_dcn_mesh_window_partition_parallel():
    from presto_tpu.parallel.mesh import make_dcn_mesh

    conn = TpchConnector(sf=0.005, units_per_split=1 << 14)
    dcn = Session({"tpch": conn}, mesh=make_dcn_mesh(2, 4),
                  properties={"gather_row_limit": 1024})
    df = dcn.sql(
        "select l_orderkey, sum(l_quantity) over (partition by l_orderkey) q "
        "from lineitem"
    )
    li = conn.table_pandas("lineitem")
    want = li.groupby("l_orderkey")["l_quantity"].transform("sum")
    got = df.sort_values(["l_orderkey", "q"]).reset_index(drop=True)
    assert len(got) == len(li)
    np.testing.assert_allclose(sorted(got["q"]), sorted(want), rtol=1e-9)


# ---------------------------------------------------------------------------
# distributed sort / topN / limit (no full replication)
# ---------------------------------------------------------------------------


def _sort_env(mesh, rows=8 * 2048, gather_limit=1024):
    """A session whose gather guard is far below the table size: any
    replicate-everything fallback in sort/topN/limit trips the guard,
    so passing proves the local-first / range-partition paths ran."""
    conn = TpchConnector(sf=0.01, units_per_split=1 << 14)
    session = Session(
        {"tpch": conn},
        mesh=mesh,
        properties={"gather_row_limit": gather_limit},
    )
    return session, conn


def test_distributed_order_by_without_replication(mesh):
    session, conn = _sort_env(mesh)
    df = session.sql(
        "select l_orderkey, l_extendedprice from lineitem order by l_extendedprice desc, l_orderkey"
    )
    li = conn.table_pandas("lineitem")
    want = li.sort_values(
        ["l_extendedprice", "l_orderkey"], ascending=[False, True], kind="stable"
    ).reset_index(drop=True)
    assert len(df) == len(want)
    np.testing.assert_array_equal(
        df["l_extendedprice"].to_numpy(), want["l_extendedprice"].to_numpy()
    )
    # orderkey must be ascending within equal-price runs; spot-check
    # global sortedness of the (price desc, key asc) pair
    p = df["l_extendedprice"].to_numpy()
    k = df["l_orderkey"].to_numpy()
    assert ((p[:-1] > p[1:]) | ((p[:-1] == p[1:]) & (k[:-1] <= k[1:]))).all()


def test_distributed_topn_without_replication(mesh):
    session, conn = _sort_env(mesh)
    df = session.sql(
        "select l_orderkey, l_extendedprice from lineitem "
        "order by l_extendedprice desc, l_orderkey limit 25"
    )
    li = conn.table_pandas("lineitem")
    want = (
        li.sort_values(
            ["l_extendedprice", "l_orderkey"], ascending=[False, True], kind="stable"
        )
        .head(25)
        .reset_index(drop=True)
    )
    np.testing.assert_array_equal(
        df["l_orderkey"].to_numpy(), want["l_orderkey"].to_numpy()
    )
    np.testing.assert_array_equal(
        df["l_extendedprice"].to_numpy(), want["l_extendedprice"].to_numpy()
    )


def test_distributed_limit_without_replication(mesh):
    session, conn = _sort_env(mesh)
    df = session.sql("select l_orderkey from lineitem limit 100")
    assert len(df) == 100
    # any 100 rows of the table qualify; check membership
    keys = set(conn.table_pandas("lineitem")["l_orderkey"].tolist())
    assert set(df["l_orderkey"].tolist()) <= keys


def test_distributed_window_partition_parallel(mesh):
    """PARTITION BY windows run via all_to_all on the partition keys
    with a gather guard far below the table size: passing proves no
    full replication happened."""
    session, conn = _sort_env(mesh)
    df = session.sql(
        "select l_orderkey, l_linenumber, "
        "       sum(l_quantity) over (partition by l_orderkey) as order_qty, "
        "       row_number() over (partition by l_orderkey order by l_linenumber) as rn "
        "from lineitem"
    )
    li = conn.table_pandas("lineitem")
    want_qty = li.groupby("l_orderkey")["l_quantity"].transform("sum")
    li = li.assign(order_qty=want_qty)
    li["rn"] = (
        li.sort_values(["l_orderkey", "l_linenumber"], kind="stable")
        .groupby("l_orderkey")
        .cumcount()
        + 1
    )
    got = df.sort_values(["l_orderkey", "l_linenumber"]).reset_index(drop=True)
    want = li.sort_values(["l_orderkey", "l_linenumber"]).reset_index(drop=True)
    np.testing.assert_allclose(
        got["order_qty"].to_numpy(), want["order_qty"].to_numpy(), rtol=1e-9
    )
    np.testing.assert_array_equal(got["rn"].to_numpy(), want["rn"].to_numpy())


def test_distributed_sort_skewed_first_key(mesh):
    """Degenerate first key (one dominant value): range partitioning
    overflows and the executor falls back without wrong results."""
    session, conn = _sort_env(mesh, gather_limit=1 << 22)
    df = session.sql(
        "select l_linenumber, l_orderkey from lineitem "
        "order by l_linenumber, l_orderkey"
    )
    li = conn.table_pandas("lineitem")
    want = li.sort_values(
        ["l_linenumber", "l_orderkey"], kind="stable"
    ).reset_index(drop=True)
    np.testing.assert_array_equal(
        df["l_orderkey"].to_numpy(), want["l_orderkey"].to_numpy()
    )


# ---------------------------------------------------------------------------
# full TPC-H over the mesh (engine vs oracle)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(QUERIES, key=lambda x: int(x[1:])))
def test_tpch_distributed_matches_oracle(env, name):
    session, tables = env
    got = session.sql(QUERIES[name])
    want = ORACLES[name](tables)
    compare(got, want, name)


# ---------------------------------------------------------------------------
# metamorphic invariants (SURVEY §7.4 #8)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_devices", [1, 2, 4])
def test_result_independent_of_mesh_shape(n_devices):
    conn = TpchConnector(sf=SF, units_per_split=1 << 14)
    local = Session({"tpch": conn}).sql(QUERIES["q3"])
    dist = Session({"tpch": conn}, mesh=make_mesh(n_devices)).sql(QUERIES["q3"])
    pd.testing.assert_frame_equal(
        local.reset_index(drop=True), dist.reset_index(drop=True),
        check_dtype=False, atol=1e-6,
    )


@pytest.mark.parametrize("name", ["q3", "q10", "q13", "q16", "q21"])
def test_repartition_join_path(mesh, name):
    """broadcast_join_row_limit=0 forces the all_to_all join path for
    every join — the FIXED_HASH distribution must agree with the
    broadcast plan and the oracle."""
    conn = TpchConnector(sf=SF, units_per_split=1 << 14)
    session = Session(
        {"tpch": conn}, properties={"broadcast_join_row_limit": 0}, mesh=mesh
    )
    got = session.sql(QUERIES[name])
    tables = {t: conn.table_pandas(t) for t in conn.tables()}
    want = ORACLES[name](tables)
    compare(got, want, name)


def test_gather_fallback_guard(mesh):
    """The remaining replicate-everything fallback (a global window —
    no PARTITION BY means one inherently serial partition) must fail
    fast with a clear error above gather_row_limit instead of silently
    multiplying memory by the mesh size (round-1 advisor finding).
    Sort/topN/limit and partitioned windows no longer replicate, so
    they run fine under the same tiny guard (tests above)."""
    import pytest

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.exec.operators import CapacityOverflow
    from presto_tpu.runtime.session import Session

    s = Session(
        {"tpch": TpchConnector(sf=0.01)},
        properties={"gather_row_limit": 16},
        mesh=mesh,
    )
    with pytest.raises(CapacityOverflow, match="gather_limit"):
        s.sql(
            "select l_orderkey, "
            "row_number() over (order by l_orderkey) rn from lineitem"
        )
    # small inputs still pass through the fallback (region: 5 rows < 16)
    df = s.sql(
        "select r_name, row_number() over (order by r_name) rn from region"
    )
    assert len(df) == 5
