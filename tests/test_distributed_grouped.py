"""Distributed grouped (bucketed) execution — the L9 spill tier on the
mesh.

Reference parity: grouped/lifespan execution + the spill decision
[SURVEY §2.1 L9 rows, §7.4 #5]. An artificially tiny
``join_build_budget_bytes`` forces every stats-estimated-oversized join
build and aggregation through the bucketed tier: host-RAM spill +
sequential per-bucket replays of the normal repartition join, and
bucket-filtered aggregation passes. Results must be identical to the
local executor's.
"""

import pandas as pd
import pytest

pytestmark = pytest.mark.slow

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.distributed import DistributedExecutor
from presto_tpu.parallel.mesh import make_mesh
from presto_tpu.runtime.session import Session

SF = 0.002
# bytes: far below every relation at SF 0.002 — including the 300-row
# customer build side now that admission estimates count NARROW physical
# widths (a single int16 key column estimates ~4 B/row -> ~1.2 KB)
TINY_BUDGET = 512

GROUPED_QUERIES = {
    # count(c_acctbal) is a BUILD-side output: a filter-only join (all
    # outputs probe-side) folds into the leaf route as a membership
    # bitmap (PR 8) and the grouped join tier under test never executes
    "inner_unique": (
        "select count(*) c, sum(o_totalprice) s, count(c_acctbal) a "
        "from orders join customer on o_custkey = c_custkey"
    ),
    "left_expand": (
        "select count(*) c, count(l_orderkey) lk from orders "
        "left join lineitem on o_orderkey = l_orderkey "
        "and l_quantity > 45"
    ),
    "full_outer": (
        "select count(*) c, count(c_custkey) ck, count(o_orderkey) ok "
        "from customer full outer join orders on c_custkey = o_custkey"
    ),
    "full_outer_swapped": (
        "select count(*) c, count(c_custkey) ck, count(o_orderkey) ok "
        "from orders full outer join customer on o_custkey = c_custkey"
    ),
    "semi": (
        "select count(*) c from customer where c_custkey in "
        "(select o_custkey from orders)"
    ),
    "anti": (
        "select count(*) c from customer where c_custkey not in "
        "(select o_custkey from orders)"
    ),
    # many-group aggregation (SortStrategy): grouped agg passes
    "big_group_by": (
        "select l_orderkey, count(*) n, sum(l_quantity) q from lineitem "
        "group by l_orderkey order by l_orderkey limit 50"
    ),
    # join feeding an aggregation, both over budget (q3 shape)
    "join_then_agg": (
        "select o_orderdate, count(*) n from orders "
        "join lineitem on o_orderkey = l_orderkey "
        "group by o_orderdate order by o_orderdate limit 20"
    ),
}


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=SF, units_per_split=1 << 14)


@pytest.fixture(scope="module")
def local(conn):
    return Session({"tpch": conn})


@pytest.mark.parametrize("name", sorted(GROUPED_QUERIES))
@pytest.mark.parametrize("n_devices", [4, 8])
def test_grouped_matches_local(conn, local, name, n_devices):
    q = GROUPED_QUERIES[name]
    want = local.sql(q)
    got = Session(
        {"tpch": conn}, mesh=make_mesh(n_devices),
        properties={"join_build_budget_bytes": TINY_BUDGET},
    ).sql(q)
    pd.testing.assert_frame_equal(
        want.reset_index(drop=True), got.reset_index(drop=True),
        check_dtype=False,
    )


def test_grouped_tier_actually_engages(conn, local, monkeypatch):
    """The tiny budget must actually route through the bucketed tier
    (guards against the trigger silently never firing)."""
    calls = {"join": 0, "agg": 0}
    orig_join = DistributedExecutor._grouped_dist_join
    orig_agg = DistributedExecutor._grouped_dist_agg

    def spy_join(self, *a, **k):
        calls["join"] += 1
        return orig_join(self, *a, **k)

    def spy_agg(self, *a, **k):
        calls["agg"] += 1
        return orig_agg(self, *a, **k)

    monkeypatch.setattr(DistributedExecutor, "_grouped_dist_join", spy_join)
    monkeypatch.setattr(DistributedExecutor, "_grouped_dist_agg", spy_agg)
    sess = Session(
        {"tpch": conn}, mesh=make_mesh(4),
        properties={"join_build_budget_bytes": TINY_BUDGET},
    )
    sess.sql(GROUPED_QUERIES["inner_unique"])
    sess.sql(GROUPED_QUERIES["big_group_by"])
    assert calls["join"] >= 1
    assert calls["agg"] >= 1


def test_grouped_row_level_full_outer(conn, local):
    """Row-level agreement through the grouped tier: unmatched rows on
    both sides must survive bucketing exactly once."""
    q = (
        "select c_custkey, o_orderkey from customer "
        "full outer join orders on c_custkey = o_custkey"
    )
    want = local.sql(q)
    got = Session(
        {"tpch": conn}, mesh=make_mesh(4),
        properties={"join_build_budget_bytes": TINY_BUDGET},
    ).sql(q)
    key = ["c_custkey", "o_orderkey"]
    pd.testing.assert_frame_equal(
        want.sort_values(key).reset_index(drop=True),
        got.sort_values(key).reset_index(drop=True),
        check_dtype=False,
    )


def test_distributed_null_group_keys_replan():
    """Grouping on a nullable key must produce a NULL group (its own
    key value) identically on the local and distributed tiers — the
    direct strategy has no NULL slot and must replan onto sort."""
    from presto_tpu.connectors.tpcds import TpcdsConnector

    c = TpcdsConnector(sf=0.002)
    q = ("select ss_store_sk, count(*) as c from store_sales "
         "group by ss_store_sk order by ss_store_sk nulls last")
    a = Session({"tpcds": c}).sql(q)
    b = Session({"tpcds": c}, mesh=make_mesh(4)).sql(q)
    pd.testing.assert_frame_equal(
        a.reset_index(drop=True), b.reset_index(drop=True),
        check_dtype=False,
    )
    # the generator emits ~2% NULL store keys: the NULL group must exist
    assert a["ss_store_sk"].isna().any()


def test_null_varchar_key_direct_replan():
    """A nullable dictionary-VARCHAR key with a small dense domain picks
    the DIRECT strategy, whose packed gid has no NULL slot — the
    NullGroupKeys replan must land on the sort strategy with NULL as its
    own group, identically on both tiers."""
    conn = TpchConnector(sf=0.002, units_per_split=1 << 12)
    q_make = ("create table nk as select nullif(n_name, 'FRANCE') as k "
              "from nation, region")
    qq = "select k, count(*) as c from nk group by k order by k nulls last"
    a_sess = Session({"tpch": conn})
    a_sess.sql(q_make)
    a = a_sess.sql(qq)
    b_sess = Session({"tpch": conn}, mesh=make_mesh(4))
    b_sess.sql(q_make)
    b = b_sess.sql(qq)
    pd.testing.assert_frame_equal(
        a.reset_index(drop=True), b.reset_index(drop=True),
        check_dtype=False,
    )
    assert a["k"].isna().any(), "NULL group must exist"
    assert int(a[a["k"].isna()]["c"].iloc[0]) == 5  # FRANCE x 5 regions
