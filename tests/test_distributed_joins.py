"""Distributed join-kind parity gate: every join kind the engine
supports must return LOCAL-identical results on a mesh, on both the
broadcast and the repartition (all_to_all) distribution.

Reference parity: the reference runs its whole SQL test corpus on the
in-process DistributedQueryRunner, which is exactly how local-only
features get caught before shipping [SURVEY §4]. Round-4 shipped FULL
OUTER and string join keys on the local tier only — distributed FULL
OUTER silently lost unmatched rows and string keys crashed (round-4
VERDICT weak #1/#2); this file is the gate that would have caught both.
"""

import pandas as pd
import pytest

pytestmark = pytest.mark.slow

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.parallel.mesh import make_mesh
from presto_tpu.runtime.session import Session

SF = 0.002

# every query here runs three ways — local, distributed (default
# broadcast-vs-repartition choice), distributed with broadcast disabled
# (forcing the all_to_all repartition path) — and all three must agree.
JOIN_QUERIES = {
    # FULL OUTER, both orientations (unmatched-build tail + unmatched
    # probe rows; customer has ~1/3 no-order customers at tiny SF)
    "full_probe_orders": (
        "select count(*) c, count(c_custkey) ck, count(o_orderkey) ok "
        "from customer full outer join orders on c_custkey = o_custkey"
    ),
    "full_probe_customer": (
        "select count(*) c, count(c_custkey) ck, count(o_orderkey) ok "
        "from orders full outer join customer on o_custkey = c_custkey"
    ),
    # FULL OUTER with grouped output (q97 shape)
    "full_grouped": (
        "select count(c_custkey) only_c, count(o_orderkey) only_o "
        "from customer full outer join orders on c_custkey = o_custkey "
        "where c_custkey is null or o_orderkey is null"
    ),
    # RIGHT OUTER (normalizes to LEFT with swapped spine)
    "right_outer": (
        "select count(*) c, count(o_orderkey) ok from orders "
        "right outer join customer on o_custkey = c_custkey"
    ),
    # LEFT OUTER against a non-unique build side
    "left_expand": (
        "select count(*) c, count(l_orderkey) lk from orders "
        "left join lineitem on o_orderkey = l_orderkey "
        "and l_quantity > 45"
    ),
    # wide string keys (BYTES > 7 bytes: hash + collision verify)
    "string_key_wide": (
        "select count(*) c from customer a join customer b "
        "on a.c_name = b.c_name"
    ),
    # narrow string keys (BYTES <= 7: exact pack) — n_name is wide,
    # use the 1-char-ish brand? TPC-H has no short CHAR key; join on a
    # substring-free fixed column instead: region r_name is 12 wide ->
    # still hash path; keep one hash self-join on a small table
    "string_key_small_table": (
        "select count(*) c from nation a join nation b on a.n_name = b.n_name"
    ),
    # semi / anti (IN / NOT IN -> SemiJoin)
    "semi": (
        "select count(*) c from customer where c_custkey in "
        "(select o_custkey from orders)"
    ),
    "anti": (
        "select count(*) c from customer where c_custkey not in "
        "(select o_custkey from orders)"
    ),
    # mark join (EXISTS OR EXISTS lowers to mark columns via dedup'd
    # LEFT joins)
    "mark_or_exists": (
        "select count(*) c from customer where "
        "exists (select 1 from orders where o_custkey = c_custkey "
        "        and o_totalprice > 100000) "
        "or exists (select 1 from lineitem where l_orderkey = c_custkey)"
    ),
    # multi-key pack (stats-covered widths, no runtime probe)
    "multi_key": (
        "select count(*) c from lineitem a join lineitem b "
        "on a.l_orderkey = b.l_orderkey and a.l_linenumber = b.l_linenumber"
    ),
    # cross-dictionary VARCHAR equi-join: codes are incomparable across
    # dictionaries; the planner must compare VALUES (the true answer is
    # 0 rows — segments and priorities never collide)
    "cross_dict_varchar": (
        "select count(*) c from customer, orders "
        "where c_mktsegment = o_orderpriority"
    ),
}


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=SF, units_per_split=1 << 14)


@pytest.fixture(scope="module")
def local(conn):
    return Session({"tpch": conn})


@pytest.mark.parametrize("name", sorted(JOIN_QUERIES))
@pytest.mark.parametrize("n_devices", [4, 8])
def test_join_kind_local_vs_distributed(conn, local, name, n_devices):
    q = JOIN_QUERIES[name]
    want = local.sql(q)
    got = Session({"tpch": conn}, mesh=make_mesh(n_devices)).sql(q)
    pd.testing.assert_frame_equal(
        want.reset_index(drop=True), got.reset_index(drop=True),
        check_dtype=False,
    )


@pytest.mark.parametrize("name", sorted(JOIN_QUERIES))
def test_join_kind_repartition_path(conn, local, name):
    """broadcast_join_row_limit=0 forces the all_to_all path for every
    join — the FIXED_HASH distribution must agree with local."""
    q = JOIN_QUERIES[name]
    want = local.sql(q)
    got = Session(
        {"tpch": conn}, mesh=make_mesh(8),
        properties={"broadcast_join_row_limit": 0},
    ).sql(q)
    pd.testing.assert_frame_equal(
        want.reset_index(drop=True), got.reset_index(drop=True),
        check_dtype=False,
    )


def test_full_outer_row_level(conn, local):
    """Row-level (not just counts): the 100 no-order customers must
    appear exactly once each, with NULL order columns."""
    q = (
        "select c_custkey, o_orderkey from customer "
        "full outer join orders on c_custkey = o_custkey"
    )
    want = local.sql(q)
    got = Session({"tpch": conn}, mesh=make_mesh(4)).sql(q)
    key = ["c_custkey", "o_orderkey"]
    pd.testing.assert_frame_equal(
        want.sort_values(key).reset_index(drop=True),
        got.sort_values(key).reset_index(drop=True),
        check_dtype=False,
    )
