"""Expression IR / evaluator tests, differentially against NumPy
(reference parity: operator.scalar.* per-function tests [SURVEY §4])."""

import numpy as np
import pytest

from presto_tpu import BIGINT, BOOLEAN, DOUBLE, Batch, Dictionary, decimal, varchar
from presto_tpu.expr import Call, Literal, col, evaluate, evaluate_predicate, lit
from presto_tpu.types import DATE, INTEGER, TypeKind


def simple_batch():
    types = {
        "a": BIGINT,
        "b": BIGINT,
        "price": decimal(12, 2),
        "disc": decimal(12, 2),
        "ship": DATE,
        "flag": varchar(),
    }
    d = Dictionary(["A", "N", "R"])
    arrays = {
        "a": np.array([1, 2, 3, 4], dtype=np.int64),
        "b": np.array([10, 20, 30, 40], dtype=np.int64),
        "price": np.array([10050, 20000, 123, 99999]),  # 100.50, 200.00, 1.23, 999.99
        "disc": np.array([5, 10, 0, 6]),  # 0.05, 0.10, 0.00, 0.06
        "ship": np.array([8766, 9000, 10000, 10591], dtype=np.int32),
        "flag": d.encode(["A", "R", "N", "R"]),
    }
    return Batch.from_numpy(arrays, types, dictionaries={"flag": d})


def test_arith_add():
    b = simple_batch()
    e = Call(BIGINT, "add", (col("a", BIGINT), col("b", BIGINT)))
    v = evaluate(e, b)
    np.testing.assert_array_equal(np.asarray(v.data), [11, 22, 33, 44])


def test_decimal_mul_scale_cap():
    b = simple_batch()
    # price * (1 - disc): decimal(,2) * decimal(,2) -> scale 4
    one = lit(1, decimal(12, 2))
    e = Call(
        decimal(38, 4),
        "mul",
        (col("price", decimal(12, 2)), Call(decimal(12, 2), "sub", (one, col("disc", decimal(12, 2))))),
    )
    v = evaluate(e, b)
    got = np.asarray(v.data) / 1e4
    want = np.array([100.50 * 0.95, 200.00 * 0.90, 1.23 * 1.00, 999.99 * 0.94])
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_comparison_and_between():
    b = simple_batch()
    e = Call(BOOLEAN, "between", (col("a", BIGINT), lit(2, BIGINT), lit(3, BIGINT)))
    mask = evaluate_predicate(e, b)
    np.testing.assert_array_equal(np.asarray(mask)[:4], [False, True, True, False])


def test_varchar_eq_literal():
    b = simple_batch()
    e = Call(BOOLEAN, "eq", (col("flag", varchar()), lit("R", varchar())))
    mask = evaluate_predicate(e, b)
    np.testing.assert_array_equal(np.asarray(mask)[:4], [False, True, False, True])


def test_varchar_eq_absent_literal_is_false():
    b = simple_batch()
    e = Call(BOOLEAN, "eq", (col("flag", varchar()), lit("Z", varchar())))
    mask = evaluate_predicate(e, b)
    assert not np.asarray(mask)[:4].any()


def test_kleene_null_semantics():
    types = {"x": BOOLEAN, "y": BOOLEAN}
    arrays = {
        "x": np.array([True, False, True, False]),
        "y": np.array([True, True, True, False]),
    }
    valids = {
        "x": np.array([True, True, False, False]),  # rows 2,3: x is NULL
        "y": np.array([True, True, True, True]),
    }
    b = Batch.from_numpy(arrays, types, valids=valids)
    v_and = evaluate(Call(BOOLEAN, "and", (col("x", BOOLEAN), col("y", BOOLEAN))), b)
    # row2: NULL AND FALSE -> FALSE (valid); row3: NULL AND FALSE -> FALSE
    assert bool(v_and.valid[3]) and not bool(v_and.data[3])
    # NULL AND TRUE -> NULL
    assert not bool(v_and.valid[2])
    v_or = evaluate(Call(BOOLEAN, "or", (col("x", BOOLEAN), col("y", BOOLEAN))), b)
    # NULL OR TRUE -> TRUE
    assert bool(v_or.valid[2]) and bool(v_or.data[2])
    # NULL OR FALSE -> NULL
    assert not bool(v_or.valid[3])


def test_date_extract_year():
    b = simple_batch()
    e = Call(INTEGER, "year", (col("ship", DATE),))
    v = evaluate(e, b)
    # 8766 days = 1994-01-01; 10591 = 1998-12-31
    got = np.asarray(v.data)[:4]
    assert got[0] == 1994
    assert got[3] == 1998


def test_like_on_dictionary():
    types = {"s": varchar()}
    d = Dictionary(["PROMO ANODIZED", "STANDARD BRUSHED", "PROMO PLATED", "ECONOMY"])
    arrays = {"s": d.encode(["PROMO PLATED", "ECONOMY", "PROMO ANODIZED", "STANDARD BRUSHED"])}
    b = Batch.from_numpy(arrays, types, dictionaries={"s": d})
    e = Call(BOOLEAN, "like", (col("s", varchar()), lit("PROMO%", varchar())))
    mask = evaluate_predicate(e, b)
    np.testing.assert_array_equal(np.asarray(mask)[:4], [True, False, True, False])


def test_case_expression():
    b = simple_batch()
    e = Call(
        BIGINT,
        "case",
        (
            Call(BOOLEAN, "gt", (col("a", BIGINT), lit(2, BIGINT))),
            lit(100, BIGINT),
            Call(BOOLEAN, "eq", (col("a", BIGINT), lit(1, BIGINT))),
            lit(7, BIGINT),
            lit(0, BIGINT),
        ),
    )
    v = evaluate(e, b)
    np.testing.assert_array_equal(np.asarray(v.data)[:4], [7, 0, 100, 100])


def test_div_by_zero_is_null():
    types = {"x": BIGINT, "y": BIGINT}
    b = Batch.from_numpy(
        {"x": np.array([10, 20]), "y": np.array([2, 0])}, types
    )
    v = evaluate(Call(DOUBLE, "div", (col("x", BIGINT), col("y", BIGINT))), b)
    assert bool(v.valid[0]) and not bool(v.valid[1])
    assert float(v.data[0]) == 5.0


def test_negative_decimal_rescale_rounding():
    """Regression: floor-division rounding must not shift negatives."""
    types = {"x": decimal(12, 1)}
    b = Batch.from_numpy({"x": np.array([-10, -11, -15, 10, 15])}, types)
    from presto_tpu.expr import Call as C

    from presto_tpu.expr import rescale_decimal

    name = rescale_decimal(0)
    v = evaluate(C(decimal(38, 0), name, (col("x", decimal(12, 1)),)), b)
    # -1.0 -> -1, -1.1 -> -1, -1.5 -> -2 (half away), 1.0 -> 1, 1.5 -> 2
    np.testing.assert_array_equal(np.asarray(v.data)[:5], [-1, -1, -2, 1, 2])


def test_varchar_between_absent_bounds():
    types = {"s": varchar()}
    d = Dictionary(["A", "N", "R"])
    b = Batch.from_numpy({"s": d.encode(["A", "N", "R"])}, types, dictionaries={"s": d})
    e = Call(
        BOOLEAN,
        "between",
        (col("s", varchar()), lit("B", varchar()), lit("M", varchar())),
    )
    mask = evaluate_predicate(e, b)
    # only values in ["B","M"]: none of A/N/R qualify
    assert not np.asarray(mask)[:3].any()
    e2 = Call(
        BOOLEAN,
        "between",
        (col("s", varchar()), lit("B", varchar()), lit("O", varchar())),
    )
    mask2 = evaluate_predicate(e2, b)
    np.testing.assert_array_equal(np.asarray(mask2)[:3], [False, True, False])
