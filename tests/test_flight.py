"""Engine flight recorder + compile-cost ledger + exchange-skew
telemetry (runtime/flight.py, cache/exec_cache.py ledger, ISSUE-12).

The contract under test:

- every query that FAILS, DEGRADES (OOM rung), RETRIES a fragment, or
  blows its deadline auto-captures a COMPLETE post-mortem — plan
  render with hints, span trace, attributed metric delta, rung/retry
  history, pool state — at ``run_plan``'s choke point, JSON-exportable
  and queryable as ``system.flight_recorder``;
- the ring respects its bound under sustained failure; recording a
  post-mortem never holds a pool reservation (autouse leak check);
- armed-but-idle overhead (successful queries, successes not captured)
  stays inside the existing <5% tracing bound;
- the executable cache's ledger measures reuse: warm runs show hits
  with ``compile_s_saved > 0`` in ``system.exec_cache``;
- the multi-round exchange reports per-destination skew: a zipfian
  repartition renders ``skew`` > 2x in EXPLAIN ANALYZE while a
  balanced stream stays ~1x, and the ratio persists into
  ``system.plan_stats`` / EXPLAIN (TYPE DISTRIBUTED) history.
"""

import json
import time

import numpy as np
import pandas as pd
import pytest

from presto_tpu.cache.exec_cache import EXEC_CACHE, trace_delta
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime import faults
from presto_tpu.runtime.errors import (
    ExceededTimeLimit,
    TransientFailure,
)
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

Q_AGG = (
    "select l_returnflag, l_linestatus, count(*) c, sum(l_quantity) q "
    "from lineitem group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)

Q_JOIN = (
    "select n_name, count(*) c, sum(s_acctbal) b "
    "from supplier join nation on s_nationkey = n_nationkey "
    "group by n_name order by n_name"
)


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=0.005)


def make_session(conn, **props):
    props.setdefault("result_cache_enabled", False)
    return Session({"tpch": conn}, properties=props)


# ---------------------------------------------------------------------------
# auto-capture triggers
# ---------------------------------------------------------------------------


def test_failed_query_captures_complete_postmortem(conn):
    s = make_session(conn)
    inj = faults.FaultInjector()
    inj.inject("scan", times=None)
    with faults.injected(inj):
        with pytest.raises(TransientFailure):
            s.sql("select n_name from nation order by n_name")
    assert len(s.flight) == 1
    rec = s.flight.latest()
    assert rec.state == "FAILED" and "failed" in rec.triggers
    assert rec.error_code == "TRANSIENT_FAILURE"
    assert "TableScan" in rec.plan_render
    assert rec.spans and any(sp["cat"] == "node" for sp in rec.spans)
    assert rec.metrics, "metric delta missing from post-mortem"
    assert rec.rung_history == [] and rec.oom_rung == 0
    # the pool reservation was released BEFORE capture
    assert rec.pool["reserved_bytes"] == 0


def test_successes_not_captured_by_default(conn):
    s = make_session(conn)
    s.sql(Q_AGG)
    assert len(s.flight) == 0


def test_success_capture_on_demand(conn):
    s = make_session(conn, flight_record_successes=True)
    s.sql(Q_AGG)
    assert len(s.flight) == 1
    rec = s.flight.latest()
    assert rec.state == "FINISHED" and rec.triggers == ("requested",)
    assert "Aggregate" in rec.plan_render and rec.spans


def test_oom_degradation_captures_rung_history(conn):
    s = make_session(conn)
    inj = faults.FaultInjector()
    inj.inject_oom("step.join_build", times=None)
    with faults.injected(inj):
        df = s.sql(Q_JOIN)
    assert len(df) > 0  # the ladder recovered
    rec = s.flight.latest()
    assert rec is not None and rec.state == "FINISHED"
    assert "degraded" in rec.triggers
    assert rec.oom_rung == 1
    # the history carries the ladder descent AND the spill decision
    # the rung re-planned into (kind-tagged so they stay separable)
    ladder = [e for e in rec.rung_history
              if e.get("kind", "ladder") == "ladder"]
    assert len(ladder) == 1
    assert ladder[0]["rung"] == 1
    assert "RESOURCE_EXHAUSTED" in ladder[0]["error"]
    planned = [e for e in rec.rung_history if e not in ladder]
    assert all(e["kind"].startswith("planned_") for e in planned)


def test_fragment_retry_captures_events(conn):
    s = make_session(conn, retry_count=2, retry_backoff_s=0.0)
    inj = faults.FaultInjector()
    inj.inject("scan", times=1)
    with faults.injected(inj):
        df = s.sql("select count(*) c from region")
    assert int(df["c"][0]) == 5  # retry succeeded
    rec = s.flight.latest()
    assert rec is not None and "retried" in rec.triggers
    assert rec.fragment_retries >= 1
    assert rec.retry_events and rec.retry_events[0]["error"] == (
        "TransientFailure")
    assert rec.retry_events[0]["site"].startswith("fragment:")


def test_deadline_blowout_captures_deadline_trigger(conn):
    s = make_session(conn, query_max_run_time=1e-6)
    with pytest.raises(ExceededTimeLimit):
        s.sql(Q_AGG)
    rec = s.flight.latest()
    assert rec is not None
    assert "deadline" in rec.triggers and "failed" in rec.triggers
    assert rec.error_code == "EXCEEDED_TIME_LIMIT"
    assert rec.deadline_s == pytest.approx(1e-6)


# ---------------------------------------------------------------------------
# export surfaces
# ---------------------------------------------------------------------------


def test_export_round_trips_json(conn, tmp_path):
    s = make_session(conn)
    inj = faults.FaultInjector()
    inj.inject("aggregation", times=None)
    with faults.injected(inj):
        with pytest.raises(TransientFailure):
            s.sql(Q_AGG)
    rec = s.flight.latest()
    p = tmp_path / "flight.json"
    text = s.export_flight_record(str(p), query_id=rec.query_id)
    assert p.read_text() == text
    d = json.loads(text)
    assert d["queryId"] == rec.query_id
    assert d["errorCode"] == "TRANSIENT_FAILURE"
    assert d["planRender"] == rec.plan_render
    assert d["spans"] and isinstance(d["spans"][0]["args"], dict)
    assert isinstance(d["metrics"], dict) and d["metrics"]
    # whole-ring export is a JSON array, newest last
    ring = json.loads(s.export_flight_record())
    assert ring[-1]["queryId"] == rec.query_id


def test_system_flight_recorder_table(conn):
    s = make_session(conn)
    inj = faults.FaultInjector()
    inj.inject("scan", times=None)
    with faults.injected(inj):
        with pytest.raises(TransientFailure):
            s.sql("select count(*) c from nation")
    df = s.sql("select query_id, state, triggers, oom_rung, spans, "
               "metric_deltas, pool_reserved_bytes from flight_recorder")
    assert len(df) == 1
    assert df["state"][0] == "FAILED"
    assert df["triggers"][0] == "failed"
    assert int(df["spans"][0]) > 0
    assert int(df["metric_deltas"][0]) > 0
    assert int(df["pool_reserved_bytes"][0]) == 0


def test_unknown_query_id_export_is_typed(conn):
    from presto_tpu.runtime.errors import UserError

    s = make_session(conn)
    with pytest.raises(UserError):
        s.export_flight_record(query_id="nope")


# ---------------------------------------------------------------------------
# ring bound + resize (the 200-round sweep)
# ---------------------------------------------------------------------------


def test_ring_respects_bound_under_200_round_sweep(conn):
    s = make_session(conn, flight_recorder_limit=16, retry_count=0)
    q = "select n_name from nation order by n_name"
    inj = faults.FaultInjector()
    inj.inject("scan", times=None)
    with faults.injected(inj):
        for _ in range(200):
            with pytest.raises(TransientFailure):
                s.sql(q)
    assert len(s.flight) == 16
    recs = s.flight.records()
    # all distinct attempts, newest retained
    assert len({r.query_id for r in recs}) == 16
    assert s.pool().reserved_bytes == 0


def test_ring_resize_takes_effect_immediately(conn):
    s = make_session(conn, flight_recorder_limit=8)
    inj = faults.FaultInjector()
    inj.inject("scan", times=None)
    with faults.injected(inj):
        for _ in range(8):
            with pytest.raises(TransientFailure):
                s.sql("select count(*) c from region")
    assert len(s.flight) == 8
    s.set_property("flight_recorder_limit", 3)
    assert len(s.flight) == 3


# ---------------------------------------------------------------------------
# steady-state overhead: armed but idle stays inside the <5% bound
# (the tests/test_trace.py pattern — min-of-N beats a loaded CI box)
# ---------------------------------------------------------------------------


def test_flight_armed_idle_overhead_under_5pct(conn):
    props = {"result_cache_enabled": False}
    # flight recorder is ALWAYS armed; successful queries with capture
    # off must cost nothing beyond the existing tracing budget
    s_on = Session({"tpch": conn}, properties=props)
    s_off = Session(
        {"tpch": conn}, properties={**props, "trace_enabled": False}
    )
    s_on.sql(Q_AGG)
    s_off.sql(Q_AGG)

    def best_of(rounds):
        on, off = [], []
        for _ in range(rounds):
            t0 = time.perf_counter()
            s_off.sql(Q_AGG)
            off.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            s_on.sql(Q_AGG)
            on.append(time.perf_counter() - t0)
        return min(on), min(off)

    for rounds in (5, 9):
        best_on, best_off = best_of(rounds)
        if best_on <= best_off * 1.05 + 0.005:
            assert len(s_on.flight) == 0  # armed, idle: nothing captured
            return
    raise AssertionError(
        f"flight-armed overhead too high: on={best_on:.4f}s "
        f"off={best_off:.4f}s"
    )


# ---------------------------------------------------------------------------
# compile-cost ledger (system.exec_cache)
# ---------------------------------------------------------------------------


def test_exec_cache_ledger_measures_amortization(conn):
    s = make_session(conn)
    s.sql(Q_AGG)  # cold: builds + first (trace+compile) calls
    with trace_delta() as td:
        s.sql(Q_AGG)  # warm: pure hits, warm calls
    assert td.traces == 0
    df = s.sql("select kind, hits, calls, cold_call_s, warm_call_s, "
               "compile_s_saved from exec_cache where hits > 0")
    assert len(df) >= 1
    assert (df["kind"].str.len() > 0).all(), "ledger lost key provenance"
    # at least one reused step measured a first-call (trace+compile)
    # wall above its warm wall: the cache demonstrably saved seconds
    assert float(df["compile_s_saved"].max()) > 0.0
    assert (df["cold_call_s"] >= df["warm_call_s"]).all()


def test_exec_cache_ledger_rows_shape():
    rows = EXEC_CACHE.stats_rows()
    assert rows, "process exec cache unexpectedly empty"
    for r in rows[:5]:
        assert set(r) == {"kind", "key", "hits", "calls", "cold_call_s",
                          "warm_call_s", "compile_s_saved", "age_s",
                          "idle_s"}
        assert r["age_s"] >= 0 and r["idle_s"] >= 0


def test_trace_delta_window_semantics(conn):
    s = make_session(conn)
    # a literal no other test uses: cold -> traces inside the window
    q = "select count(*) c from orders where o_orderkey < 424243"
    with trace_delta() as td:
        s.sql(q)
        cold = td.traces
    with trace_delta() as td2:
        s.sql(q)
    # under plan templates the literal rides a slot, so SOME prior
    # template may already be warm — the invariant is the warm window
    # is strictly no worse than the cold one, and zero after repeat
    assert td2.traces == 0
    assert cold >= td2.traces


# ---------------------------------------------------------------------------
# exchange-skew telemetry (virtual 8-device mesh; slow tier like the
# other distributed suites)
# ---------------------------------------------------------------------------


def _skew_frame(n_rows: int, zipf: bool, rng) -> pd.DataFrame:
    if zipf:
        # one hot key owns ~85% of rows: whatever partition it hashes
        # to receives most of the exchange
        keys = np.where(rng.random(n_rows) < 0.85, 7,
                        rng.integers(0, 64, n_rows))
    else:
        keys = np.arange(n_rows) % 64  # uniform over 64 keys
    return pd.DataFrame({"k": keys.astype(np.int64),
                         "v": rng.integers(0, 100, n_rows)})


@pytest.mark.slow
def test_zipfian_repartition_skew_visible_everywhere(conn, rng):
    """Skewed keys -> EXPLAIN ANALYZE skew > 2x + exchange.skew
    histogram + plan_stats history + EXPLAIN (TYPE DISTRIBUTED) header;
    balanced keys -> ~1x."""
    from presto_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    s = Session({"tpch": conn}, mesh=mesh, properties={
        "result_cache_enabled": False,
        "broadcast_join_row_limit": 0,  # force the repartition join
    })
    mem = s.catalog.connector("memory")
    mem.create_table("skewed", _skew_frame(4096, True, rng))
    mem.create_table("balanced", _skew_frame(4096, False, rng))
    mem.create_table("dim", pd.DataFrame(
        {"dk": np.arange(64, dtype=np.int64),
         "dv": np.arange(64, dtype=np.int64)}))

    q = ("select count(*) c, sum(dv) s from {} join dim on k = dk")
    before = REGISTRY.snapshot().get("exchange.skew.count", 0)
    out_skew = s.explain_analyze(q.format("skewed"))
    out_bal = s.explain_analyze(q.format("balanced"))
    after = REGISTRY.snapshot().get("exchange.skew.count", 0)
    assert after > before, "exchange.skew histogram not populated"

    import re

    def join_skew(rendered: str) -> float:
        m = re.search(r"Join .*skew ([\d.]+)x", rendered)
        assert m, f"no skew rendered on the Join:\n{rendered}"
        return float(m.group(1))

    assert join_skew(out_skew) > 2.0, out_skew
    assert join_skew(out_bal) < 2.0, out_bal

    # persisted beside est/actual per node in system.plan_stats
    ps = s.sql("select node_type, skew from plan_stats where skew > 2")
    assert len(ps) >= 1 and "Join" in set(ps["node_type"])

    # recurring skew becomes plan-visible: the second run made the
    # fingerprint recurrent (runs >= 2), so the distributed rendering
    # carries the observed ratio in the fragment header
    s.execute(q.format("skewed"))
    dist = s.explain_distributed(q.format("skewed"))
    assert "skew~" in dist, dist


@pytest.mark.slow
def test_skew_lands_in_failure_postmortem(conn, rng):
    """A distributed run that dies AFTER its exchanges keeps the skew
    evidence: the post-mortem carries the per-site summaries."""
    from presto_tpu.parallel.mesh import make_mesh
    from presto_tpu.runtime.errors import PrestoError

    s = Session({"tpch": conn}, mesh=make_mesh(8), properties={
        "result_cache_enabled": False,
        "broadcast_join_row_limit": 0,
        "degrade_to_local": False,
        "retry_count": 0,
    })
    mem = s.catalog.connector("memory")
    mem.create_table("skewed2", _skew_frame(2048, True, rng))
    mem.create_table("dim2", pd.DataFrame(
        {"dk": np.arange(64, dtype=np.int64)}))
    q = "select count(*) c from skewed2 join dim2 on k = dk"
    s.sql(q)  # warm pass proves the plan works
    inj = faults.FaultInjector()
    inj.inject("aggregation", times=None)
    with faults.injected(inj):
        with pytest.raises(PrestoError):
            s.sql(q)
    rec = s.flight.latest()
    assert rec is not None and rec.state == "FAILED"
    sites = {e["site"] for e in rec.exchange_skew}
    assert {"join.probe", "join.build"} <= sites, rec.exchange_skew
    probe = [e for e in rec.exchange_skew if e["site"] == "join.probe"]
    assert probe[0]["skew"] > 2.0
    assert probe[0]["rows"] > 0 and probe[0]["bytes"] > 0
