"""PlanFragmenter: sound cardinality bounds, plan-time join
distribution, fragment rendering, and distributed-executor parity when
the plan-proven broadcast fast path fires (no live_count sync).

Reference parity: PlanFragmenter / AddExchanges /
DetermineJoinDistributionType [SURVEY §2.1 L3 row, §3.1].
"""

import pandas as pd
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.plan.fragmenter import fragment_plan, upper_bound_rows
from presto_tpu.plan import nodes as N
from presto_tpu.runtime.session import Session

SF = 0.002

Q3ISH = (
    "select o_orderdate, sum(l_extendedprice * (1 - l_discount)) rev "
    "from lineitem join orders on l_orderkey = o_orderkey "
    "where o_orderdate < date '1995-03-15' "
    "group by o_orderdate order by rev desc limit 10"
)


@pytest.fixture(scope="module")
def session():
    return Session({"tpch": TpchConnector(sf=SF)})


def _the_join(plan):
    found = []

    def walk(n):
        if isinstance(n, N.Join):
            found.append(n)
        for c in n.children:
            walk(c)

    walk(plan)
    assert len(found) == 1
    return found[0]


def test_upper_bound_is_sound_not_estimated(session):
    # a Filter must NOT shrink the bound (estimate_rows divides by 3)
    plan = session.plan(
        "select count(*) from orders where o_orderdate < date '1995-01-01'")
    rows = session.catalog.connector("tpch").row_count("orders")
    assert upper_bound_rows(plan, session.catalog) == rows


def test_keyless_aggregate_bounds_at_least_one_row(session):
    # a keyless aggregate emits exactly one row even over an EMPTY
    # input, so a child bound of 0 (limit 0) must not propagate — a
    # 0-row bound would let a consumer size a buffer with no room for
    # the row that always arrives
    plan = session.plan(
        "select count(*) c from (select * from nation limit 0) t")
    assert upper_bound_rows(plan, session.catalog) == 1


def test_unique_join_bounds_by_probe_side(session):
    plan = session.plan(
        "select count(*) from lineitem join orders on l_orderkey = o_orderkey")
    li = session.catalog.connector("tpch").row_count("lineitem")
    assert upper_bound_rows(plan, session.catalog) == li


def test_q3_build_side_is_plan_time_broadcast(session):
    plan = session.plan(Q3ISH)
    fp = fragment_plan(plan, session.catalog, broadcast_limit=1 << 21,
                       join_build_budget=1 << 30)
    join = _the_join(plan)
    assert fp.join_strategy[id(join)] == "broadcast"
    # the build side is FILTERED (o_orderdate predicate), so the bound
    # is loose: the sync-free fast path must NOT engage (it would
    # mis-size the replication compaction) — runtime decides as before
    assert not fp.join_fits_budget[id(join)]
    assert fp.join_rows_ub[id(join)] == \
        session.catalog.connector("tpch").row_count("orders")
    # the build side lives in its own replicated fragment
    kinds = [ex.kind for f in fp.fragments for _, ex in f.consumes]
    assert "broadcast" in kinds
    assert "hash" in kinds  # the grouped-aggregate exchange


def test_unfiltered_dimension_build_takes_fast_path(session):
    plan = session.plan(
        "select count(*) from supplier join nation "
        "on s_nationkey = n_nationkey")
    fp = fragment_plan(plan, session.catalog, broadcast_limit=1 << 21,
                       join_build_budget=1 << 30)
    join = _the_join(plan)
    assert fp.join_strategy[id(join)] == "broadcast"
    assert fp.join_fits_budget[id(join)]  # unfiltered scan: exact bound


def test_root_sort_renders_gather(session):
    out = session.explain_distributed(
        "select l_orderkey, l_quantity from lineitem "
        "order by l_quantity limit 5")
    assert "gather <- fragment" in out.replace("[", "").replace("]", "")
    assert out.count("TableScan[tpch.lineitem]") == 1


def test_large_build_is_auto(session):
    plan = session.plan(
        "select count(*) from lineitem join orders on l_orderkey = o_orderkey")
    join = _the_join(plan)
    fp = fragment_plan(plan, session.catalog,
                       broadcast_limit=10,  # force: orders exceed this
                       join_build_budget=1 << 30)
    assert fp.join_strategy[id(join)] == "auto"


def test_unproven_broadcast_renders_tentative(session):
    """A join whose row UB fits the broadcast limit but whose byte
    budget is NOT plan-time proven can still spill at runtime: EXPLAIN
    must render it dist=broadcast? (tentative), not dist=broadcast."""
    plan = session.plan(
        "select count(*) from lineitem join orders on l_orderkey = o_orderkey")
    join = _the_join(plan)
    fp = fragment_plan(plan, session.catalog, broadcast_limit=1 << 21,
                       join_build_budget=1)  # nothing fits one byte
    assert fp.join_strategy[id(join)] == "broadcast"
    assert not fp.join_fits_budget[id(join)]
    assert "dist=broadcast?" in fp.render()
    # the proven case still renders plainly
    fp2 = fragment_plan(plan, session.catalog, broadcast_limit=1 << 21,
                        join_build_budget=1 << 40)
    assert fp2.join_fits_budget[id(join)]
    out2 = fp2.render()
    assert "dist=broadcast" in out2 and "dist=broadcast?" not in out2


def test_render_mentions_every_fragment_once(session):
    out = session.explain_distributed(Q3ISH)
    assert "Fragment 0 [single]" in out
    assert "dist=broadcast" in out
    # each TableScan appears in exactly one fragment
    assert out.count("TableScan[tpch.orders]") == 1
    assert out.count("TableScan[tpch.lineitem]") == 1


@pytest.mark.slow
def test_plan_proven_broadcast_matches_local():
    from presto_tpu.parallel.mesh import make_mesh

    conn = TpchConnector(sf=SF)
    local = Session({"tpch": conn})
    dist = Session({"tpch": conn}, mesh=make_mesh(4))
    want = local.sql(Q3ISH)
    got = dist.sql(Q3ISH)
    pd.testing.assert_frame_equal(
        want.reset_index(drop=True), got.reset_index(drop=True),
        check_dtype=False)
