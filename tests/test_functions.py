"""Round-5 scalar-function breadth, differentially vs Python/NumPy
(reference parity: operator.scalar per-function tests [SURVEY §4]).

Every function is exercised through BOTH representations where it
applies: dictionary VARCHAR (derived-dictionary transforms) and
fixed-width BYTES (vectorized kernels), plus the SQL surface for a
sample of each family.
"""

import datetime

import numpy as np
import pytest

from presto_tpu import BIGINT, Batch, Dictionary, decimal, varchar
from presto_tpu.expr import (
    Call,
    Literal,
    cast_varchar_fn,
    col,
    evaluate,
    evaluate_predicate,
    lit,
    parse_date_fn,
    split_part_fn,
    substr_dict_fn,
)
from presto_tpu.types import (
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    TypeKind,
    fixed_bytes,
)

WORDS = ["  hello  ", "world", " spaced", "trail ", "a,b,c", "", "MiXeD"]


def str_batch():
    d = Dictionary(WORDS)
    codes = d.encode(WORDS)
    raw = np.zeros((len(WORDS), 12), np.uint8)
    for i, w in enumerate(WORDS):
        b = w.encode()
        raw[i, : len(b)] = np.frombuffer(b, np.uint8)
    return Batch.from_numpy(
        {"s": codes, "b": raw},
        {"s": varchar(), "b": fixed_bytes(12)},
        dictionaries={"s": d},
    ), d


def decode_bytes(mat):
    return ["".join(chr(c) for c in row if c != 0) for row in np.asarray(mat)]


def decode_dict(v):
    codes = np.asarray(v.data)
    return [str(v.dictionary.values[c]) for c in codes]


# ---------------------------------------------------------------------------
# string transforms
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fn,pyfn", [
    ("trim", lambda s: s.strip(" ")), ("ltrim", lambda s: s.lstrip(" ")),
    ("rtrim", lambda s: s.rstrip(" ")),
    ("reverse", lambda s: s[::-1]),
    ("upper", str.upper), ("lower", str.lower),
])
def test_string_transform_dict(fn, pyfn):
    b, d = str_batch()
    v = evaluate(Call(varchar(), fn, (col("s", varchar()),)), b)
    assert decode_dict(v) == [pyfn(w) for w in WORDS]


@pytest.mark.parametrize("fn,pyfn", [
    ("trim", lambda s: s.strip(" ")), ("ltrim", lambda s: s.lstrip(" ")),
    ("rtrim", lambda s: s.rstrip(" ")),
    ("reverse", lambda s: s[::-1]),
])
def test_string_transform_bytes(fn, pyfn):
    b, _ = str_batch()
    v = evaluate(Call(fixed_bytes(12), fn, (col("b", fixed_bytes(12)),)), b)
    assert decode_bytes(v.data) == [pyfn(w) for w in WORDS]


def test_length_both_paths():
    b, _ = str_batch()
    v = evaluate(Call(INTEGER, "length", (col("s", varchar()),)), b)
    np.testing.assert_array_equal(np.asarray(v.data), [len(w) for w in WORDS])
    vb = evaluate(Call(INTEGER, "length", (col("b", fixed_bytes(12)),)), b)
    # BYTES storage cannot represent trailing spaces -> rtrim'd length
    np.testing.assert_array_equal(
        np.asarray(vb.data), [len(w.rstrip()) for w in WORDS]
    )


def test_strpos_both_paths():
    b, _ = str_batch()
    needle = Literal(varchar(), "l")
    v = evaluate(Call(INTEGER, "strpos", (col("s", varchar()), needle)), b)
    np.testing.assert_array_equal(
        np.asarray(v.data), [w.find("l") + 1 for w in WORDS]
    )
    vb = evaluate(
        Call(INTEGER, "strpos", (col("b", fixed_bytes(12)), needle)), b
    )
    np.testing.assert_array_equal(
        np.asarray(vb.data), [w.find("l") + 1 for w in WORDS]
    )


def test_replace_and_split_part():
    b, _ = str_batch()
    v = evaluate(
        Call(varchar(), "replace",
             (col("s", varchar()), Literal(varchar(), "l"),
              Literal(varchar(), "L"))), b)
    assert decode_dict(v) == [w.replace("l", "L") for w in WORDS]
    fn = split_part_fn(",", 2)
    v2 = evaluate(Call(varchar(), fn, (col("s", varchar()),)), b)

    def sp(w):
        parts = w.split(",")
        return parts[1] if len(parts) >= 2 else ""

    assert decode_dict(v2) == [sp(w) for w in WORDS]


def test_substr_dict_general():
    b, _ = str_batch()
    fn = substr_dict_fn(2, 3)
    v = evaluate(Call(varchar(), fn, (col("s", varchar()),)), b)
    assert decode_dict(v) == [w[1:4] for w in WORDS]
    neg = substr_dict_fn(-3, 2)
    v2 = evaluate(Call(varchar(), neg, (col("s", varchar()),)), b)
    assert decode_dict(v2) == [w[-3:-1] if len(w) >= 3 else "" for w in WORDS]


def test_regexp_like():
    b, _ = str_batch()
    v = evaluate_predicate(
        Call(BOOLEAN, "regexp_like",
             (col("s", varchar()), Literal(varchar(), "^[a-z]+$"))), b)
    import re

    rx = re.compile("^[a-z]+$")
    np.testing.assert_array_equal(
        np.asarray(v)[: len(WORDS)], [rx.search(w) is not None for w in WORDS]
    )


# ---------------------------------------------------------------------------
# math
# ---------------------------------------------------------------------------


def num_batch():
    return Batch.from_numpy(
        {"x": np.array([4.0, 0.25, 9.0, 2.0]),
         "i": np.array([-5, 0, 7, 100], np.int64),
         "d": np.array([1050, -275, 0, 99999], np.int64)},
        {"x": DOUBLE, "i": BIGINT, "d": decimal(12, 2)},
    )


def test_math_family():
    b = num_batch()
    x = col("x", DOUBLE)
    for fn, want in [
        ("exp", np.exp([4, 0.25, 9, 2])),
        ("ln", np.log([4, 0.25, 9, 2])),
        ("log10", np.log10([4, 0.25, 9, 2])),
        ("log2", np.log2([4, 0.25, 9, 2])),
    ]:
        v = evaluate(Call(DOUBLE, fn, (x,)), b)
        np.testing.assert_allclose(np.asarray(v.data)[:4], want, rtol=1e-5)
    v = evaluate(Call(DOUBLE, "power", (x, lit(2, BIGINT))), b)
    np.testing.assert_allclose(np.asarray(v.data)[:4], [16, 0.0625, 81, 4],
                               rtol=1e-6)
    v = evaluate(Call(INTEGER, "sign", (col("i", BIGINT),)), b)
    np.testing.assert_array_equal(np.asarray(v.data)[:4], [-1, 0, 1, 1])
    v = evaluate(Call(DOUBLE, "truncate",
                      (Call(DOUBLE, "cast_double", (col("d", decimal(12, 2)),)),)), b)
    np.testing.assert_allclose(np.asarray(v.data)[:4], [10, -2, 0, 999])


def test_greatest_least_null_semantics():
    b = Batch.from_numpy(
        {"a": np.array([1, 5, 3], np.int64), "b": np.array([2, 4, 9], np.int64)},
        {"a": BIGINT, "b": BIGINT},
        valids={"a": np.array([True, True, False]), "b": None},
    )
    g = evaluate(Call(BIGINT, "greatest", (col("a", BIGINT), col("b", BIGINT))), b)
    np.testing.assert_array_equal(np.asarray(g.data)[:2], [2, 5])
    assert not bool(np.asarray(g.valid)[2])  # NULL argument -> NULL
    l = evaluate(Call(BIGINT, "least", (col("a", BIGINT), col("b", BIGINT))), b)
    np.testing.assert_array_equal(np.asarray(l.data)[:2], [1, 4])


# ---------------------------------------------------------------------------
# dates — differential vs datetime over a broad sample
# ---------------------------------------------------------------------------

EPOCH = datetime.date(1970, 1, 1)


def date_batch():
    rng = np.random.default_rng(11)
    days = rng.integers(-30000, 40000, 500).astype(np.int32)
    # edge cases: leap days, year/month boundaries
    edges = [datetime.date(2000, 2, 29), datetime.date(1999, 12, 31),
             datetime.date(2001, 1, 1), datetime.date(1970, 1, 1),
             datetime.date(2024, 2, 29), datetime.date(1900, 3, 1)]
    days = np.concatenate([days, [(e - EPOCH).days for e in edges]])
    return Batch.from_numpy({"d": days}, {"d": DATE}), [
        EPOCH + datetime.timedelta(days=int(v)) for v in days
    ]


def test_date_parts():
    b, dates = date_batch()
    d = col("d", DATE)
    for fn, pyf in [
        ("quarter", lambda x: (x.month + 2) // 3),
        ("day_of_week", lambda x: x.isoweekday()),
        ("day_of_year", lambda x: x.timetuple().tm_yday),
    ]:
        v = evaluate(Call(INTEGER, fn, (d,)), b)
        np.testing.assert_array_equal(
            np.asarray(v.data), [pyf(x) for x in dates], err_msg=fn
        )


def test_date_trunc_and_last_day():
    from presto_tpu.expr import date_trunc_fn

    b, dates = date_batch()
    d = col("d", DATE)
    for unit, pyf in [
        ("month", lambda x: x.replace(day=1)),
        ("year", lambda x: x.replace(month=1, day=1)),
        ("quarter", lambda x: x.replace(month=((x.month - 1) // 3) * 3 + 1, day=1)),
        ("week", lambda x: x - datetime.timedelta(days=x.isoweekday() - 1)),
    ]:
        v = evaluate(Call(DATE, date_trunc_fn(unit), (d,)), b)
        np.testing.assert_array_equal(
            np.asarray(v.data), [(pyf(x) - EPOCH).days for x in dates],
            err_msg=unit,
        )
    v = evaluate(Call(DATE, "last_day_of_month", (d,)), b)

    def last_day(x):
        nxt = (x.replace(day=28) + datetime.timedelta(days=4)).replace(day=1)
        return nxt - datetime.timedelta(days=1)

    np.testing.assert_array_equal(
        np.asarray(v.data), [(last_day(x) - EPOCH).days for x in dates]
    )


def test_date_add_diff():
    from presto_tpu.expr import date_add_fn, date_diff_fn

    b, dates = date_batch()
    d = col("d", DATE)
    # day / week via timedelta
    v = evaluate(Call(DATE, date_add_fn("day"), (lit(45, INTEGER), d)), b)
    np.testing.assert_array_equal(
        np.asarray(v.data),
        [(x + datetime.timedelta(days=45) - EPOCH).days for x in dates],
    )
    # calendar month addition with clamping
    v = evaluate(Call(DATE, date_add_fn("month"), (lit(13, INTEGER), d)), b)

    def addm(x, n):
        tot = x.year * 12 + (x.month - 1) + n
        y, m = divmod(tot, 12)
        m += 1
        import calendar

        day = min(x.day, calendar.monthrange(y, m)[1])
        return datetime.date(y, m, day)

    np.testing.assert_array_equal(
        np.asarray(v.data), [(addm(x, 13) - EPOCH).days for x in dates]
    )
    ref = lit("2000-06-15", DATE)
    v = evaluate(Call(BIGINT, date_diff_fn("day"), (d, ref)), b)
    np.testing.assert_array_equal(
        np.asarray(v.data),
        [(datetime.date(2000, 6, 15) - x).days for x in dates],
    )
    v = evaluate(Call(BIGINT, date_diff_fn("month"), (d, ref)), b)

    def diffm(a, bb):
        m = (bb.year * 12 + bb.month) - (a.year * 12 + a.month)
        if bb >= a and bb.day < a.day:
            m -= 1
        if bb < a and bb.day > a.day:
            m += 1
        return m

    np.testing.assert_array_equal(
        np.asarray(v.data),
        [diffm(x, datetime.date(2000, 6, 15)) for x in dates],
    )
    # weeks truncate toward zero (SQL), never floor
    v = evaluate(Call(BIGINT, date_diff_fn("week"), (d, ref)), b)
    np.testing.assert_array_equal(
        np.asarray(v.data),
        [int((datetime.date(2000, 6, 15) - x).days / 7) for x in dates],
    )


# ---------------------------------------------------------------------------
# casts
# ---------------------------------------------------------------------------


def test_cast_int_to_varchar():
    b = num_batch()
    fn = cast_varchar_fn(20)
    v = evaluate(Call(fixed_bytes(20), fn, (col("i", BIGINT),)), b)
    assert decode_bytes(v.data)[:4] == ["-5", "0", "7", "100"]


def test_cast_decimal_to_varchar():
    b = num_batch()
    fn = cast_varchar_fn(14)
    v = evaluate(Call(fixed_bytes(14), fn, (col("d", decimal(12, 2)),)), b)
    assert decode_bytes(v.data)[:4] == ["10.50", "-2.75", "0.00", "999.99"]


def test_cast_date_to_varchar_roundtrip():
    b, dates = date_batch()
    fn = cast_varchar_fn(10)
    v = evaluate(Call(fixed_bytes(10), fn, (col("d", DATE),)), b)
    assert decode_bytes(v.data) == [x.isoformat() for x in dates]


def test_cast_varchar_to_date():
    texts = ["1995-03-15", "2020-02-29", "bogus", "1969-07-20"]
    d = Dictionary(texts)
    b = Batch.from_numpy({"s": d.encode(texts)}, {"s": varchar()},
                         dictionaries={"s": d})
    v = evaluate(Call(DATE, parse_date_fn(), (col("s", varchar()),)), b)
    got = np.asarray(v.data)
    valid = np.asarray(v.valid)
    for i, t in enumerate(texts):
        try:
            want = (datetime.date.fromisoformat(t) - EPOCH).days
            assert valid[i] and got[i] == want
        except ValueError:
            assert not valid[i]


# ---------------------------------------------------------------------------
# SQL surface samples (one per family, through the full engine)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def session():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runtime.session import Session

    yield Session({"tpch": TpchConnector(sf=0.001, units_per_split=1 << 14)})


def test_sql_string_functions(session):
    out = session.sql(
        "select n_name, length(trim(n_name)) as l, substr(n_name, 1, 3) as p "
        "from nation order by n_name limit 3"
    )
    assert list(out["p"]) == ["ALG", "ARG", "BRA"]
    assert list(out["l"]) == [7, 9, 6]


def test_sql_position_and_replace(session):
    out = session.sql(
        "select position('ER' in n_name) as p, replace(n_name, 'A', '@') as r "
        "from nation where n_name = 'GERMANY'"
    )
    assert list(out["p"]) == [2]
    assert list(out["r"]) == ["GERM@NY"]


def test_sql_date_functions(session):
    out = session.sql(
        "select o_orderkey, quarter(o_orderdate) as q, "
        "date_diff('day', date '1995-01-01', o_orderdate) as dd, "
        "date_add('month', 2, o_orderdate) as dm "
        "from orders order by o_orderkey limit 1"
    )
    od = session.sql(
        "select o_orderkey, o_orderdate from orders "
        "order by o_orderkey limit 1"
    )["o_orderdate"][0]
    od = datetime.date.fromisoformat(str(od)[:10])
    assert out["q"][0] == (od.month + 2) // 3
    assert out["dd"][0] == (od - datetime.date(1995, 1, 1)).days


def test_sql_math_and_cast(session):
    out = session.sql(
        "select greatest(2, 5, 3) as g, least(2, 5, 3) as l, "
        "power(2, 10) as p, sign(-7) as s, mod(17, 5) as m, "
        "cast(42 as varchar) as cv"
    )
    assert out["g"][0] == 5 and out["l"][0] == 2
    assert out["p"][0] == 1024.0
    assert out["s"][0] == -1 and out["m"][0] == 2
    assert str(out["cv"][0]).strip() == "42"


def test_substr_negative_out_of_range():
    b, _ = str_batch()
    fn = substr_dict_fn(-20, 2)  # |start| > every length -> empty
    v = evaluate(Call(varchar(), fn, (col("s", varchar()),)), b)
    assert decode_dict(v) == ["" for _ in WORDS]


def test_cast_negative_subunit_decimal():
    b = Batch.from_numpy(
        {"d": np.array([-50, -5, 50], np.int64)}, {"d": decimal(12, 2)},
    )
    v = evaluate(Call(fixed_bytes(8), cast_varchar_fn(8),
                      (col("d", decimal(12, 2)),)), b)
    assert decode_bytes(v.data) == ["-0.50", "-0.05", "0.50"]


def test_sql_substr_negative(session):
    out = session.sql(
        "select n_name, substr(n_name, -3) as tail from nation "
        "where n_name = 'FRANCE'"
    )
    assert list(out["tail"]) == ["NCE"]



# ---------------------------------------------------------------------------
# TIMESTAMP (int64 microseconds since epoch)
# ---------------------------------------------------------------------------


def ts_batch():
    stamps = ["1995-03-15 13:45:30", "1970-01-01 00:00:00",
              "2024-02-29 23:59:59", "1969-12-31 22:30:00"]
    us = [int((np.datetime64(t.replace(" ", "T"), "us")
               - np.datetime64("1970-01-01T00:00:00", "us")).astype(np.int64))
          for t in stamps]
    from presto_tpu.types import TIMESTAMP

    return Batch.from_numpy({"t": np.array(us, np.int64)},
                            {"t": TIMESTAMP}), stamps


def test_timestamp_extract_parts():
    from presto_tpu.types import TIMESTAMP

    b, stamps = ts_batch()
    t = col("t", TIMESTAMP)
    want = [datetime.datetime.fromisoformat(s) for s in stamps]
    for fn, pyf in [("year", lambda x: x.year), ("month", lambda x: x.month),
                    ("day", lambda x: x.day), ("hour", lambda x: x.hour),
                    ("minute", lambda x: x.minute),
                    ("second", lambda x: x.second)]:
        v = evaluate(Call(INTEGER, fn, (t,)), b)
        np.testing.assert_array_equal(
            np.asarray(v.data), [pyf(x) for x in want], err_msg=fn)


def test_timestamp_trunc_and_cast():
    from presto_tpu.expr import cast_varchar_fn, date_trunc_fn
    from presto_tpu.types import TIMESTAMP

    b, stamps = ts_batch()
    t = col("t", TIMESTAMP)
    v = evaluate(Call(TIMESTAMP, date_trunc_fn("hour"), (t,)), b)
    want = [datetime.datetime.fromisoformat(s).replace(minute=0, second=0)
            for s in stamps]
    epoch = datetime.datetime(1970, 1, 1)
    np.testing.assert_array_equal(
        np.asarray(v.data),
        [int((x - epoch).total_seconds() * 1_000_000) for x in want])
    r = evaluate(Call(fixed_bytes(19), cast_varchar_fn(19), (t,)), b)
    assert decode_bytes(r.data) == stamps


def test_timestamp_sql_surface(session):
    out = session.sql(
        "select timestamp '1995-03-15 13:45:30' as t, "
        "hour(timestamp '1995-03-15 13:45:30') as h, "
        "cast(date '1995-03-15' as timestamp) as d2t, "
        "date_trunc('minute', timestamp '1995-03-15 13:45:30') as tm"
    )
    assert out["h"][0] == 13
    assert "1995-03-15" in str(out["t"][0])
