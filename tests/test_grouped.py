"""Grouped (bucketed) join execution with host-RAM offload — L9.

The round-2 VERDICT done-criterion: a join whose build side exceeds an
artificially small budget completes correctly, in sequential
HBM-bounded bucket passes (SURVEY §2.1 L9 rows, §7.4 #5).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.session import Session


Q3ISH = (
    "select o_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue "
    "from orders, lineitem where o_orderkey = l_orderkey "
    "and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15' "
    "group by o_orderkey order by revenue desc, o_orderkey limit 20"
)


def _oracle(conn):
    o = conn.table_pandas("orders", ["o_orderkey", "o_orderdate"])
    li = conn.table_pandas(
        "lineitem", ["l_orderkey", "l_shipdate", "l_extendedprice", "l_discount"]
    )
    o = o[o.o_orderdate < np.datetime64("1995-03-15")]
    li = li[li.l_shipdate > np.datetime64("1995-03-15")]
    j = li.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    g = j.groupby("o_orderkey", as_index=False)["revenue"].sum()
    return g.sort_values(["revenue", "o_orderkey"], ascending=[False, True],
                         kind="stable").head(20).reset_index(drop=True)


def test_grouped_join_over_tiny_budget_matches_unbudgeted():
    conn = TpchConnector(sf=0.01, units_per_split=1 << 12)
    # ~4 KB budget: the orders build side (thousands of rows) must
    # split into many buckets
    tiny = Session(
        {"tpch": conn}, properties={"join_build_budget_bytes": 4096}
    )
    got = tiny.sql(Q3ISH)
    want = _oracle(conn)
    np.testing.assert_array_equal(
        got["o_orderkey"].to_numpy(), want["o_orderkey"].to_numpy()
    )
    np.testing.assert_allclose(
        got["revenue"].to_numpy(), want["revenue"].to_numpy(), rtol=1e-9
    )


def test_grouped_execution_actually_buckets(monkeypatch):
    """The tiny budget must actually route through the grouped path
    with >1 bucket (not silently fall back to the resident join)."""
    import presto_tpu.exec.grouped as G

    calls = []
    real = G.spill_stream

    def spy(stream, key, nbuckets, **kw):
        calls.append(nbuckets)
        return real(stream, key, nbuckets, **kw)

    monkeypatch.setattr(G, "spill_stream", spy)
    conn = TpchConnector(sf=0.01, units_per_split=1 << 12)
    s = Session({"tpch": conn}, properties={"join_build_budget_bytes": 4096})
    # Q3ISH (not a bare count(*)): a filter-only count folds into the
    # fused leaf route and never reaches the join strategy point
    s.sql(Q3ISH)
    assert calls and all(b > 1 for b in calls), calls


def test_grouped_left_join_emits_unmatched_probe_rows():
    """Probe-outer rows in buckets with an empty build side must still
    come out with NULL build columns."""
    conn = TpchConnector(sf=0.005, units_per_split=1 << 12)
    q = (
        "select l_orderkey, o_orderdate from lineitem "
        "left join orders on l_orderkey = o_orderkey "
        "and o_orderdate < date '1993-01-01' "
        "order by l_orderkey limit 30"
    )
    tiny = Session({"tpch": conn}, properties={"join_build_budget_bytes": 2048})
    big = Session({"tpch": conn})
    got = tiny.sql(q)
    want = big.sql(q)
    assert got.equals(want)
