"""Serving-tier health observability (runtime/health.py,
runtime/devices.py, ISSUE-18).

The contract under test:

- device telemetry: ``sample_devices()`` reports one row per local
  device even on CPU meshes; ``system.device_stats`` is queryable and
  the dispatch ledger attributes fragment-dispatch wall per device;
- trace propagation: a W3C ``traceparent`` parses to its trace-id
  (malformed degrades, never rejects), and the REQUEST_TRACE context
  honors the client identifier end to end with the documented
  ``X-Presto-Trace`` > traceparent > server-generated precedence;
- tenant SLOs: rolling burn rates per tenant with TenantSpec-level
  objective overrides, queryable as ``system.slo``;
- the anomaly watchdog: armed-but-quiet costs <5% and trips ZERO
  breaches; a seeded latency regression trips EXACTLY ONE
  ``health_breach`` (latch + cooldown) carrying a complete
  flight-recorder post-mortem of the worst in-flight query;
- metric hygiene: every literal counter/timer/histogram family the
  engine fires has a METRIC_HELP entry (dynamically-suffixed families
  are exempt by construction).
"""

import pathlib
import re
import threading
import time

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.devices import (
    DISPATCH_WALL,
    headroom_bytes,
    peak_bytes,
    sample_devices,
)
from presto_tpu.runtime.health import HealthMonitor, SloTracker
from presto_tpu.runtime.lifecycle import QueryManager
from presto_tpu.runtime.metrics import METRIC_HELP, REGISTRY
from presto_tpu.runtime.session import Session
from presto_tpu.server.frontend import (
    QueryServer,
    _parse_traceparent,
    _trace_context,
)
from presto_tpu.server.scheduler import TenantSpec

CONN = TpchConnector(sf=0.005)

Q_FAST = "select count(*) c from nation"


def make_session(**props):
    props.setdefault("result_cache_enabled", False)
    return Session({"tpch": CONN}, properties=props)


def counter(name: str) -> float:
    return REGISTRY.snapshot().get(name, 0.0)


# ---------------------------------------------------------------------------
# metric hygiene: METRIC_HELP covers every literal family
# ---------------------------------------------------------------------------

def test_metric_help_covers_every_literal_family():
    """Every literal ``REGISTRY.counter/timer/histogram("name")`` call
    site in the engine (and the bench harness) must have a METRIC_HELP
    entry — scrape consumers read the HELP line, and a missing one
    means a family was added without documenting what it measures.
    f-string families (per-tenant/per-device suffixes) are exempt: the
    pattern only matches plain string literals."""
    root = pathlib.Path(__file__).resolve().parent.parent
    pat = re.compile(
        r'REGISTRY\.(?:counter|timer|histogram)\(\s*"([^"{]+)"')
    files = sorted((root / "presto_tpu").rglob("*.py"))
    files.append(root / "bench.py")
    fired = set()
    for path in files:
        fired.update(pat.findall(path.read_text()))
    missing = sorted(fired - set(METRIC_HELP))
    assert not missing, (
        f"{len(missing)} metric families fired without a METRIC_HELP "
        f"entry: {missing}")


# ---------------------------------------------------------------------------
# device telemetry
# ---------------------------------------------------------------------------

def test_device_sampling_rows_and_system_table():
    rows = sample_devices()
    assert rows, "no local devices sampled"
    for r in rows:
        assert set(r) == {"device_id", "platform", "bytes_in_use",
                          "peak_bytes", "bytes_limit", "dispatch_wall_s",
                          "dispatches"}
    # CPU-safe scalar accessors: ints/None, never raises
    assert isinstance(peak_bytes(), int)
    assert headroom_bytes() is None or isinstance(headroom_bytes(), int)

    s = make_session()
    wall0, n0 = DISPATCH_WALL.snapshot()
    s.sql(Q_FAST)  # at least one fragment dispatch lands in the ledger
    wall1, n1 = DISPATCH_WALL.snapshot()
    assert n1 > n0 and wall1 >= wall0
    df = s.sql("select device_id, platform, bytes_in_use, "
               "dispatch_wall_s, dispatches from device_stats")
    assert len(df) == len(rows)
    assert int(df["dispatches"][0]) >= n1 - n0


# ---------------------------------------------------------------------------
# trace propagation plumbing
# ---------------------------------------------------------------------------

def test_traceparent_parses_and_malformed_degrades():
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    assert _parse_traceparent(f"00-{tid}-00f067aa0ba902b7-01") == tid
    # malformed headers degrade to None (never reject the statement)
    for bad in (None, "", "garbage", f"00-{tid[:-1]}-00f067aa0ba902b7-01",
                f"00-{'0' * 32}-00f067aa0ba902b7-01",
                f"zz-{tid}-00f067aa0ba902b7-01",
                f"00-{tid}-shortspan-01"):
        assert _parse_traceparent(bad) is None, bad


def test_trace_context_precedence():
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    # explicit token wins over the traceparent id
    ctx = _trace_context(token="my-token", traceparent_id=tid, force=True)
    assert ctx["token"] == "my-token"
    assert ctx["trace_id"] == tid
    assert ctx["force_trace"] is True
    # traceparent alone: its id is both token and trace_id
    ctx = _trace_context(traceparent_id=tid)
    assert ctx["token"] == tid and ctx["trace_id"] == tid
    # neither: server generates both (trace_id always 32-hex)
    ctx = _trace_context()
    assert len(ctx["trace_id"]) == 32 and not ctx["force_trace"]
    # a 32-hex X-Presto-Trace token doubles as the trace id
    ctx = _trace_context(token=tid.upper())
    assert ctx["trace_id"] == tid


# ---------------------------------------------------------------------------
# tenant SLOs
# ---------------------------------------------------------------------------

def test_slo_tracker_burn_rates_and_overrides():
    slo = SloTracker(latency_objective_s=1.0, freshness_objective_s=10.0,
                     window=8, overrides={"gold": (0.1, None)})
    # default tenant: 3 good, 1 breach -> burn 0.25
    for dt in (0.2, 0.3, 0.4, 2.0):
        slo.observe_latency("web", dt)
    # gold's tighter override: the same 0.2s is already a breach
    slo.observe_latency("gold", 0.2)
    slo.observe_freshness("web", 3.0)
    rows = {r["tenant"]: r for r in slo.snapshot()}
    assert rows["web"]["latency_objective_s"] == 1.0
    assert rows["web"]["latency_good"] == 3
    assert rows["web"]["latency_breach"] == 1
    assert rows["web"]["latency_burn_rate"] == pytest.approx(0.25)
    assert rows["web"]["freshness_burn_rate"] == 0.0
    assert rows["gold"]["latency_objective_s"] == pytest.approx(0.1)
    assert rows["gold"]["latency_burn_rate"] == 1.0
    # worst-across-tenants burn feeds the watchdog's burn reason
    assert slo.burn_rate() == 1.0
    assert slo.burn_rate("web") == pytest.approx(0.25)


def test_slo_rides_serving_layer_to_system_table():
    qs = QueryServer({"tpch": CONN},
                     tenants=[TenantSpec("gold", slo_latency_s=120.0)],
                     properties={"result_cache_enabled": False,
                                 "health_monitor": False})
    try:
        qs.execute(Q_FAST, tenant="gold")
        qs.execute(Q_FAST, tenant="walkin")
        df = qs.session.sql("select tenant, latency_objective_s, "
                            "latency_good, latency_breach from slo")
        rows = {t: (obj, good, breach) for t, obj, good, breach in
                zip(df["tenant"], df["latency_objective_s"],
                    df["latency_good"], df["latency_breach"])}
        # the TenantSpec override reached the tracker; both tenants
        # landed observations through run_plan's lifecycle hook
        assert rows["gold"][0] == pytest.approx(120.0)
        assert rows["gold"][1] >= 1 and rows["gold"][2] == 0
        assert rows["walkin"][1] >= 1
    finally:
        qs.shutdown(drain_timeout_s=10)


# ---------------------------------------------------------------------------
# watchdog: armed-but-quiet is cheap and silent
# ---------------------------------------------------------------------------

def test_watchdog_armed_quiet_overhead_under_5pct():
    """The full observability stack ARMED (watchdog thread sampling,
    device telemetry stamping, SLO tracking) on a quiet baseline: zero
    breaches, and best-of-N wall inside the 5% overhead bound vs the
    same serving stack with all of it off."""
    breaches0 = counter("health.breach")
    qs_on = QueryServer({"tpch": CONN},
                        properties={"result_cache_enabled": False,
                                    "health_interval_s": 0.05})
    qs_off = QueryServer({"tpch": CONN},
                         properties={"result_cache_enabled": False,
                                     "health_monitor": False,
                                     "device_telemetry": False})
    assert qs_on.health is not None and qs_on.health.running()
    assert qs_off.health is None
    try:
        qs_on.execute(Q_FAST)   # warm both compile caches
        qs_off.execute(Q_FAST)

        def best_of(rounds):
            on, off = [], []
            for _ in range(rounds):
                t0 = time.perf_counter()
                qs_off.execute(Q_FAST)
                off.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                qs_on.execute(Q_FAST)
                on.append(time.perf_counter() - t0)
            return min(on), min(off)

        for rounds in (5, 9, 13):
            best_on, best_off = best_of(rounds)
            if best_on <= best_off * 1.05 + 0.005:
                break
        else:
            raise AssertionError(
                f"armed-quiet watchdog overhead too high: "
                f"on={best_on:.4f}s off={best_off:.4f}s")
        # quiet baseline: the sampler ran, nothing breached
        time.sleep(0.15)  # let the 0.05s cadence land a few samples
        assert qs_on.health.snapshot(), "watchdog never sampled"
        assert qs_on.health.breaches() == []
        assert counter("health.breach") == breaches0
    finally:
        qs_on.shutdown(drain_timeout_s=10)
        qs_off.shutdown(drain_timeout_s=10)
    assert not qs_on.health.running()


# ---------------------------------------------------------------------------
# watchdog: a seeded regression trips exactly one breach + post-mortem
# ---------------------------------------------------------------------------

def test_seeded_latency_regression_trips_exactly_one_breach(monkeypatch):
    """Deterministic breach-detection drive (no sampler thread):
    build a clean baseline, seed a latency regression via a run_plan
    delay, and assert the latch fires EXACTLY ONE ``health_breach``
    whose flight record is a complete post-mortem (trigger, spans,
    live trace) of the worst in-flight query."""
    breaches0 = counter("health.breach")
    # warm the process-wide executable cache in a throwaway session so
    # the monitored session's history never contains a cold-compile
    # outlier (which would inflate the baseline the seeded regression
    # must beat)
    warm = make_session(trace_enabled=True)
    warm.sql(Q_FAST)
    warm.sql("select count(*) c2 from region")

    s = make_session(trace_enabled=True)
    mon = HealthMonitor(s, min_samples=3, p99_factor=3.0,
                        cooldown_s=1000.0)  # never start(): sample() only
    s.health = mon  # system.health backing store

    # baseline: measure the (warm) fast query, then ring up clean samples
    for _ in range(5):
        s.sql(Q_FAST)
    for _ in range(4):
        assert mon.sample()["breach"] == 0
    fast_p99 = max(i.execution_s for i in s.history.infos())
    delay = max(0.5, 5.0 * fast_p99)  # comfortably past the 3x factor

    # seed the regression INSIDE the execution window (run_plan's
    # admission wait re-stamps started_mono, so a delay there would
    # land in QUEUED time and never move p99)
    orig_ladder = QueryManager._run_with_oom_ladder

    def slow_ladder(self, executor, plan, info, recorder, ctx):
        time.sleep(delay)
        return orig_ladder(self, executor, plan, info, recorder, ctx)

    monkeypatch.setattr(QueryManager, "_run_with_oom_ladder", slow_ladder)
    s.sql(Q_FAST)  # one completed slow query: history p99 regresses

    # keep a second slow query IN FLIGHT so the breach capture has a
    # live target (worst in-flight = this one)
    errors: list = []

    def run_inflight():
        try:
            s.sql("select count(*) c2 from region")
        except Exception as e:  # noqa: BLE001 — surfaced to the assert
            errors.append(e)

    t = threading.Thread(target=run_inflight, daemon=True)
    t.start()
    deadline = time.monotonic() + 30
    while (not s.query_manager.inflight_snapshot()
           and time.monotonic() < deadline):
        time.sleep(0.005)
    inflight = s.query_manager.inflight_snapshot()
    assert inflight, "seeded query never registered in flight"

    cur = mon.sample()
    assert cur["breach"] == 1 and "p99" in cur["reason"]
    # the incident persists across samples; the latch holds it to ONE
    for _ in range(3):
        assert mon.sample()["breach"] == 0
    t.join(timeout=60)
    assert not t.is_alive() and not errors, errors

    events = mon.breaches()
    assert len(events) == 1
    assert counter("health.breach") == breaches0 + 1
    assert events[0]["query_id"] == inflight[0]["info"].query_id
    assert events[0]["baseline_p99_s"] > 0

    # the post-mortem: flight record under the health_breach trigger,
    # carrying the in-flight query's live trace
    recs = [r for r in s.flight.records()
            if "health_breach" in r.triggers]
    assert len(recs) == 1
    rec = recs[0]
    assert rec.query_id == events[0]["query_id"]
    assert rec.trace_enabled and rec.spans
    assert rec.plan_render and "reserved_bytes" in rec.pool

    # the ring is queryable with the breach row intact
    df = s.sql("select breach, reason from health")
    assert int(sum(df["breach"])) == 1
    assert "p99" in str(df["reason"][int(df["breach"].idxmax())])
