"""Differential suite for the fused Pallas join route + runtime join
filters (sideways information passing) — ISSUE-7.

Contract under test: the fused VMEM-table probe and the probe-scan
runtime filters are OPTIMIZATIONS — results must be bit-identical to
the generic XLA join paths with both toggles in every combination,
across narrow/wide keys, NULL keys, empty build sides, skewed keys,
narrowed dtypes at their bound edges, route-ineligible shapes, and
the OOM ladder's forced-grouped rung (the route counters assert which
path actually ran). Degradation must be loud (typed fallback +
``join.pallas_fallback`` counter), never silent; the APPROXIMATE
sketch mode must be flagged in QueryInfo and EXPLAIN, never implied.
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from presto_tpu.batch import Batch
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch.queries import QUERIES
from presto_tpu.exec.joins import BuildOutput, JoinBuildOperator, LookupJoinOperator
from presto_tpu.exec.pipeline import BatchSource, Pipeline
from presto_tpu.expr import col
from presto_tpu.ops import pallas_join
from presto_tpu.ops.hashing import bloom_build, bloom_test
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session
from presto_tpu.types import BIGINT, INTEGER

SF = 0.005


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=SF)


def _session(conn, **props):
    return Session({"tpch": conn},
                   properties={"result_cache_enabled": False, **props})


def _frames_equal(a: pd.DataFrame, b: pd.DataFrame):
    assert a.equals(b), f"frames differ:\n{a}\nvs\n{b}"


# ---------------------------------------------------------------------------
# Operator-level: kernel vs generic, every eligible mode
# ---------------------------------------------------------------------------


def _run_probe(build_arrays, probe_arrays, spec, jt, outs=(), unique=True,
               cap=2048, build_valids=None, probe_valids=None,
               build_count=None):
    """One join through JoinBuildOperator/LookupJoinOperator with an
    explicit pallas spec; returns (DataFrame, strategy). INTEGER
    (int32) storage throughout — the narrow representation the kernel
    accepts (int64 canonical keys are a fallback case, tested
    separately)."""
    types = {k: INTEGER for k in build_arrays} | {k: INTEGER for k in probe_arrays}
    bb = Batch.from_numpy(build_arrays, types, capacity=1024,
                          valids=build_valids, count=build_count)
    pb = Batch.from_numpy(probe_arrays, types, capacity=cap,
                          valids=probe_valids)
    b = JoinBuildOperator(col("bk", INTEGER), pallas=spec)
    Pipeline(BatchSource([bb]), [b]).run()
    op = LookupJoinOperator(b, col("pk", INTEGER), outs, jt, unique=unique,
                            out_capacity=None if unique or jt in ("semi", "anti")
                            else 4 * cap)
    out = Pipeline(BatchSource([pb]), [op]).run()
    df = pd.concat([o.to_pandas() for o in out]).reset_index(drop=True)
    return df.sort_values(list(df.columns)).reset_index(drop=True), op._strategy


CASES = [
    ("semi", (), "exists"),
    ("anti", (), "exists"),
    ("inner", (), "exists"),
    ("inner", (BuildOutput("bval", "bval"),), "payload"),
    ("left", (BuildOutput("bval", "bval"),), "payload"),
]


@pytest.mark.parametrize("jt,outs,mode", CASES)
def test_kernel_vs_generic_bit_identical(jt, outs, mode, rng):
    """Every pallas mode against the generic probe on the same data —
    including NULL probe keys and a NULL-masked build key."""
    n_b, n_p = 150, 1500
    bk = rng.choice(np.arange(-40, 400), size=n_b, replace=False)
    bval = rng.integers(-(1 << 30), 1 << 30, size=n_b)
    pk = rng.integers(-80, 460, size=n_p)
    pvalid = rng.random(n_p) < 0.9  # NULL probe keys
    bvalid = rng.random(n_b) < 0.9  # NULL build keys
    spec = pallas_join.PallasJoinSpec(mode, -40, 399,
                                      payload=tuple(bo.source for bo in outs))
    args = dict(
        build_arrays={"bk": bk, "bval": bval},
        probe_arrays={"pk": pk, "pval": np.arange(n_p)},
        jt=jt, outs=outs,
        build_valids={"bk": bvalid}, probe_valids={"pk": pvalid},
    )
    got, strat = _run_probe(spec=spec, **args)
    assert strat == "pallas", "fused route did not fire"
    want, gstrat = _run_probe(spec=None, **args)
    assert gstrat != "pallas"
    _frames_equal(got, want)


def test_bound_edge_keys_int16_storage(rng):
    """NARROWED int16 storage at its bound edges: keys span the full
    int16 domain, kernel vs generic identical (the in-range comparison
    must not wrap)."""
    from presto_tpu.types import narrow_physical

    # -32768 is the int16 extreme, which narrowing keeps free (exact
    # negation) — the narrowed int16 domain is [-32767, 32767]
    t16 = narrow_physical(BIGINT, -32767, 32767)
    assert str(t16.phys) == "int16", t16.phys
    bk = np.array([-32767, -1, 0, 1, 32767], dtype=np.int64)
    pk = np.array([-32767, -32766, -2, 0, 2, 32766, 32767] * 200,
                  dtype=np.int64)
    spec = pallas_join.PallasJoinSpec("exists", -32767, 32767)
    # exists at full int16 domain: 65536 keys -> 2048 words, in budget
    assert pallas_join.exists_words(1 << 16)
    types = {"bk": t16, "bval": BIGINT, "pk": t16, "pval": BIGINT}
    bb = Batch.from_numpy({"bk": bk, "bval": bk}, types, capacity=1024)
    pb = Batch.from_numpy({"pk": pk, "pval": np.arange(len(pk))}, types,
                          capacity=2048)

    def run(spec):
        b = JoinBuildOperator(col("bk", t16), pallas=spec)
        Pipeline(BatchSource([bb]), [b]).run()
        op = LookupJoinOperator(b, col("pk", t16), (), "semi")
        out = Pipeline(BatchSource([pb]), [op]).run()
        df = pd.concat([o.to_pandas() for o in out]).reset_index(drop=True)
        return df.sort_values(list(df.columns)).reset_index(drop=True), \
            op._strategy

    got, strat = run(spec)
    assert strat == "pallas"
    want, gstrat = run(None)
    assert gstrat != "pallas"
    _frames_equal(got, want)


def test_int64_canonical_keys_fall_back(rng):
    """Canonical int64 key storage is OUTSIDE the kernel contract:
    the probe must degrade loudly to the generic path, identical
    results."""
    bk = np.arange(1, 64, dtype=np.int64)
    pk = np.arange(0, 128, dtype=np.int64).repeat(16)
    types = {"bk": BIGINT, "bval": BIGINT, "pk": BIGINT, "pval": BIGINT}
    bb = Batch.from_numpy({"bk": bk, "bval": bk}, types, capacity=1024)
    pb = Batch.from_numpy({"pk": pk, "pval": np.arange(len(pk))}, types,
                          capacity=2048)
    before = REGISTRY.snapshot().get("join.pallas_fallback", 0)
    b = JoinBuildOperator(col("bk", BIGINT),
                          pallas=pallas_join.PallasJoinSpec("exists", 1, 64))
    Pipeline(BatchSource([bb]), [b]).run()
    op = LookupJoinOperator(b, col("pk", BIGINT), (), "semi")
    out = Pipeline(BatchSource([pb]), [op]).run()
    assert op._strategy != "pallas"
    assert REGISTRY.snapshot().get("join.pallas_fallback", 0) > before
    got = pd.concat([o.to_pandas() for o in out])
    assert sorted(got["pk"].unique().tolist()) == bk.tolist()


def test_empty_build_side(rng):
    """A build batch with ZERO live rows: pallas and generic agree
    (semi keeps nothing, anti keeps everything)."""
    bk = np.array([1, 2, 3], dtype=np.int64)
    pk = np.array([1, 2, 3, 4] * 300, dtype=np.int64)
    for jt in ("semi", "anti"):
        args = dict(build_arrays={"bk": bk, "bval": bk},
                    probe_arrays={"pk": pk, "pval": np.arange(len(pk))},
                    jt=jt, outs=(), build_count=0)
        got, strat = _run_probe(
            spec=pallas_join.PallasJoinSpec("exists", 1, 16), **args)
        assert strat == "pallas"
        want, _ = _run_probe(spec=None, **args)
        _frames_equal(got, want)


def test_domain_violation_falls_back_loudly(rng):
    """A live build key OUTSIDE the advisory stats domain discards the
    fused tables (counter fires) and the generic probe answers."""
    bk = np.array([1, 5, 999], dtype=np.int64)  # 999 violates [1, 100]
    pk = np.array([1, 5, 999, 7] * 300, dtype=np.int64)
    before = REGISTRY.snapshot().get("join.pallas_fallback", 0)
    args = dict(build_arrays={"bk": bk, "bval": bk},
                probe_arrays={"pk": pk, "pval": np.arange(len(pk))},
                jt="semi", outs=())
    got, strat = _run_probe(
        spec=pallas_join.PallasJoinSpec("exists", 1, 100), **args)
    assert strat != "pallas", "violated domain must not route pallas"
    assert REGISTRY.snapshot().get("join.pallas_fallback", 0) > before
    want, _ = _run_probe(spec=None, **args)
    _frames_equal(got, want)


def test_unblockable_capacity_falls_back(rng):
    """A probe batch whose capacity cannot block (cap 512 < 1024)
    degrades to the generic probe per batch, loudly."""
    bk = np.arange(1, 40, dtype=np.int64)
    pk = np.arange(0, 60, dtype=np.int64)
    before = REGISTRY.snapshot().get("join.pallas_fallback", 0)
    args = dict(build_arrays={"bk": bk, "bval": bk},
                probe_arrays={"pk": pk, "pval": np.arange(len(pk))},
                jt="semi", outs=(), cap=512)
    got, strat = _run_probe(
        spec=pallas_join.PallasJoinSpec("exists", 1, 64), **args)
    assert strat != "pallas"
    assert REGISTRY.snapshot().get("join.pallas_fallback", 0) > before
    want, _ = _run_probe(spec=None, **args)
    _frames_equal(got, want)


# ---------------------------------------------------------------------------
# SQL-level differentials: filters x kernel toggles, 2x2
# ---------------------------------------------------------------------------

_JOIN_QUERIES = {
    "q3": QUERIES["q3"],
    "semi": ("select count(*) c from lineitem where l_orderkey in "
             "(select o_orderkey from orders where o_orderdate < "
             "date '1995-03-15')"),
    "anti": ("select count(*) c from lineitem where l_orderkey not in "
             "(select o_orderkey from orders where o_orderdate >= "
             "date '1998-01-01')"),
    "left": ("select o_orderkey, o_custkey, c_name from orders "
             "left join customer on o_custkey = c_custkey "
             "order by o_orderkey limit 50"),
}


@pytest.mark.parametrize("qname", sorted(_JOIN_QUERIES))
def test_sql_toggles_bit_identical(conn, qname):
    q = _JOIN_QUERIES[qname]
    frames = []
    for filters in (True, False):
        for kernel in (True, False):
            s = _session(conn, runtime_join_filters=filters,
                         pallas_join=kernel)
            frames.append(s.sql(q))
    for f in frames[1:]:
        _frames_equal(frames[0], f)


def test_q3_routes_pallas_and_prunes(conn):
    before = REGISTRY.snapshot()
    s = _session(conn)
    s.sql(QUERIES["q3"])
    after = REGISTRY.snapshot()
    assert after.get("exec.pallas_join_route", 0) > before.get(
        "exec.pallas_join_route", 0), "Q3 did not hit the fused join route"
    assert after.get("join.filter_rows_pruned", 0) > before.get(
        "join.filter_rows_pruned", 0), "Q3 runtime filter pruned nothing"
    assert after.get("join.filter_selectivity.count", 0) > before.get(
        "join.filter_selectivity.count", 0)


def test_forced_grouped_oom_rung(conn):
    """The OOM ladder's forced out-of-core rung: results identical to
    the un-degraded run, and the fused route is NOT taken (the spill
    tier is the robustness backstop). Rung 1 re-plans into hybrid
    (shrunk resident set) rather than fully-grouped — either spill
    mode satisfies the backstop contract."""
    from presto_tpu.plan.prune import prune

    s = _session(conn)
    q = _JOIN_QUERIES["semi"]
    want = s.sql(q)
    ex = s.executor
    ex.oom_rung = 1  # what runtime/lifecycle.degrade_for_oom sets
    before = REGISTRY.snapshot()
    plan = prune(s.analyzer.analyze(__import__(
        "presto_tpu.sql.parser", fromlist=["parse"]).parse(q)))
    got = ex.run(plan)
    after = REGISTRY.snapshot()
    _frames_equal(want, got)
    spilled = sum(after.get(f"join.strategy.{m}", 0)
                  - before.get(f"join.strategy.{m}", 0)
                  for m in ("hybrid", "grouped"))
    assert spilled > 0, "OOM rung did not route the spill tier"
    assert after.get("exec.pallas_join_route", 0) == before.get(
        "exec.pallas_join_route", 0), "forced spill rung must not route pallas"


def test_explain_renders_strategy_and_filters(conn):
    s = _session(conn)
    out = s.explain(QUERIES["q3"])
    assert "strategy=" in out
    assert "runtime_filter=['l_orderkey']" in out


# ---------------------------------------------------------------------------
# approx_join (sketch mode)
# ---------------------------------------------------------------------------


def test_approx_join_superset_semantics(rng):
    """Sketch-mode semi join: every true match survives (no false
    negatives); any extras are Bloom false positives, i.e. the result
    is a superset of the exact one."""
    bk = rng.choice(np.arange(0, 1 << 22), size=500, replace=False)
    pk = rng.integers(0, 1 << 22, size=3000)
    spec = pallas_join.PallasJoinSpec("sketch", nbits=pallas_join.SKETCH_BITS)
    args = dict(build_arrays={"bk": bk.astype(np.int64), "bval": bk.astype(np.int64)},
                probe_arrays={"pk": pk.astype(np.int64),
                              "pval": np.arange(len(pk))},
                jt="semi", outs=(), cap=4096)
    got, strat = _run_probe(spec=spec, **args)
    assert strat == "pallas"
    want, _ = _run_probe(spec=None, **args)
    got_keys = set(map(tuple, got.to_numpy().tolist()))
    want_keys = set(map(tuple, want.to_numpy().tolist()))
    assert want_keys <= got_keys, "sketch dropped a true match"


def test_approx_join_property_changes_fingerprint(conn):
    from presto_tpu.cache.fingerprint import plan_fingerprint

    s = _session(conn)
    plan = s.plan(_JOIN_QUERIES["semi"])
    exact = plan_fingerprint(plan, s.catalog, {"approx_join": False}, None)
    approx = plan_fingerprint(plan, s.catalog, {"approx_join": True}, None)
    assert exact != approx, "approx results could leak into exact caches"


def test_anti_never_routes_sketch(rng):
    """A sketch false positive would DROP anti-join rows: the operator
    must refuse the sketch for anti even when handed a spec."""
    bk = np.arange(0, 50, dtype=np.int64)
    pk = np.arange(0, 2000, dtype=np.int64)
    spec = pallas_join.PallasJoinSpec("sketch", nbits=pallas_join.SKETCH_BITS)
    got, strat = _run_probe(
        spec=spec,
        build_arrays={"bk": bk, "bval": bk},
        probe_arrays={"pk": pk, "pval": np.arange(len(pk))},
        jt="anti", outs=())
    assert strat != "pallas"
    want, _ = _run_probe(
        spec=None,
        build_arrays={"bk": bk, "bval": bk},
        probe_arrays={"pk": pk, "pval": np.arange(len(pk))},
        jt="anti", outs=())
    _frames_equal(got, want)


# ---------------------------------------------------------------------------
# Bloom primitives
# ---------------------------------------------------------------------------


def test_bloom_no_false_negatives(rng):
    keys = rng.integers(-(1 << 31), 1 << 31, size=5000).astype(np.int64)
    live = rng.random(5000) < 0.8
    words = bloom_build(jnp.asarray(keys), jnp.asarray(live), 1 << 15)
    hit = np.asarray(bloom_test(words, jnp.asarray(keys)))
    assert hit[live].all(), "bloom_test missed an inserted key"


def test_skewed_keys_bit_identical(rng):
    """Heavily SKEWED distributions on both sides: ~90% of probe rows
    share one hot key (present in the build) and the duplicate-build
    expansion path sees a hot build key too — fused vs generic must
    stay bit-identical, and duplicate builds must never route the
    unique-only payload mode."""
    n_p = 2000
    # probe: 90% hot key 7, the rest uniform over [0, 256)
    hot = rng.random(n_p) < 0.9
    pk = np.where(hot, 7, rng.integers(0, 256, size=n_p)).astype(np.int64)
    bk = np.concatenate([[7], rng.choice(np.arange(8, 200), size=40,
                                         replace=False)]).astype(np.int64)
    args = dict(build_arrays={"bk": bk, "bval": bk * 10},
                probe_arrays={"pk": pk, "pval": np.arange(n_p)},
                jt="semi", outs=())
    got, strat = _run_probe(
        spec=pallas_join.PallasJoinSpec("exists", 0, 255), **args)
    assert strat == "pallas", "skewed probe keys must still route fused"
    want, gstrat = _run_probe(spec=None, **args)
    assert gstrat != "pallas"
    _frames_equal(got, want)
    # duplicate-heavy build (hot build key 7 repeated) through the
    # non-unique expansion join: payload mode is unique-only, so the
    # operator must refuse the fused route and expand identically
    bk_dup = np.concatenate([np.full(3, 7), np.arange(100, 140)]).astype(
        np.int64)
    args = dict(build_arrays={"bk": bk_dup, "bval": np.arange(len(bk_dup))},
                probe_arrays={"pk": pk, "pval": np.arange(n_p)},
                jt="inner", outs=(BuildOutput("bval", "bval"),),
                unique=False, cap=2048)
    got, strat = _run_probe(
        spec=pallas_join.PallasJoinSpec("payload", 0, 255,
                                        payload=("bval",)), **args)
    assert strat == "expand", "duplicate build keys must not route payload"
    want, _ = _run_probe(spec=None, **args)
    _frames_equal(got, want)


def test_approx_flagged_in_queryinfo_and_explain(conn):
    """ISSUE-7 acceptance: the approximate mode is reported DISTINCTLY
    — ``QueryInfo.approximate`` on the run that probed a sketch, and
    ``strategy=sketch(approx)`` in EXPLAIN — so exact results are
    never silently degraded. The build key domain here (2^21) exceeds
    the exact exists-table budget (2^19), forcing the sketch."""
    import pandas as pd

    s = _session(conn, approx_join=True)
    mem = s.catalog.connector("memory")
    mem.create_table("bigdom", pd.DataFrame(
        {"k": np.array([0, 1 << 21], dtype=np.int64)}))
    mem.create_table("bigprobe", pd.DataFrame(
        {"pk": (np.arange(1500, dtype=np.int64) * 131) % (1 << 21)}))
    q = "select count(*) c from bigprobe where pk in (select k from bigdom)"
    assert "strategy=sketch(approx)" in s.explain(q)
    before = REGISTRY.snapshot().get("exec.pallas_join_route", 0)
    df, info = s.execute(q)
    assert info.approximate, "sketch run must flag QueryInfo.approximate"
    assert '"approximate": true' in info.to_json()
    assert REGISTRY.snapshot().get("exec.pallas_join_route", 0) > before
    # the exact session: same tables, no sketch, no flag, and the
    # approximate count can only ever be >= the exact one (Bloom
    # false positives ADD rows, never drop them)
    s2 = _session(conn)
    mem2 = s2.catalog.connector("memory")
    mem2.create_table("bigdom", pd.DataFrame(
        {"k": np.array([0, 1 << 21], dtype=np.int64)}))
    mem2.create_table("bigprobe", pd.DataFrame(
        {"pk": (np.arange(1500, dtype=np.int64) * 131) % (1 << 21)}))
    exact_df, exact_info = s2.execute(q)
    assert not exact_info.approximate
    assert "sketch" not in s2.explain(q)
    assert int(df["c"][0]) >= int(exact_df["c"][0])


def test_minmax_memo_shared_across_joins(conn):
    """ISSUE-7 satellite: repeated key-expr min/max lookups within one
    query share one QUERY-scoped memo (the seed rebuilt the dict per
    ``join_key_exprs`` call) — the second normalization of the same
    key pair pays ZERO runtime readbacks and fires the
    ``joinkeys.minmax_memo_hits`` counter."""
    from presto_tpu.exec.joinkeys import join_key_exprs
    from presto_tpu.expr import BIGINT, Call
    from presto_tpu.plan import nodes as N

    s = _session(conn)
    plan = s.plan("select count(*) c from lineitem l join partsupp p on "
                  "l.l_partkey = p.ps_partkey and l.l_suppkey = p.ps_suppkey")

    def find_join(n):
        if isinstance(n, N.Join):
            return n
        for c in n.children:
            r = find_join(c)
            if r is not None:
                return r

    join = find_join(plan)
    # wrap the first key pair in a function plan/bounds cannot bound,
    # so the width ladder must fall back to runtime min/max — the path
    # the memo (and behind it the cross-query stats cache) fronts
    lk = [Call(BIGINT, "opaque_probe_fn", (join.left_keys[0],)),
          join.left_keys[1]]
    rk = [Call(BIGINT, "opaque_probe_fn", (join.right_keys[0],)),
          join.right_keys[1]]
    calls = []

    def rm(side, key):
        calls.append(side)
        return (0, 1000)

    memo: dict = {}

    def normalize():
        return join_key_exprs(
            lk, rk, {}, catalog=s.catalog, lnode=join.left, rnode=join.right,
            runtime_minmax=rm, minmax_memo=memo)

    before = REGISTRY.snapshot().get("joinkeys.minmax_memo_hits", 0)
    normalize()
    n_first = len(calls)
    assert memo, "the stats-less key pair must populate the memo"
    # second join over the same keys in the same query: memo hits, no
    # new readbacks
    normalize()
    assert len(calls) == n_first, "memo reuse must skip runtime readbacks"
    assert REGISTRY.snapshot().get("joinkeys.minmax_memo_hits", 0) > before


def test_string_keys_never_get_filters(conn):
    """Regression: string/bytes join keys NORMALIZE (pack/hash) during
    execution — build bounds over the hashed domain must never prune
    the raw scan column. Registration must refuse, and the wide-string
    join must still answer correctly with filters enabled."""
    from presto_tpu.plan import nodes as N
    from presto_tpu.plan.joinfilters import filter_edges

    s = _session(conn)
    # c_mktsegment is a dictionary VARCHAR; a self-join on it exercises
    # the VARCHAR exclusion structurally
    q = ("select count(*) c from customer a join "
         "(select distinct c_mktsegment m from customer) b "
         "on a.c_mktsegment = b.m")
    plan = s.plan(q)
    edges = filter_edges(plan)
    assert not any(isinstance(j, (N.Join, N.SemiJoin)) and
                   j.left_keys[0].dtype.kind.name == "VARCHAR"
                   for j, _s, _c in edges), \
        "a VARCHAR join key received a runtime filter"
    df = s.sql(q)
    off = _session(conn, runtime_join_filters=False).sql(q)
    _frames_equal(df, off)


def test_declared_interval_prunes_without_runtime_stats(conn):
    """The satellite fix: a probe scan prunes against the build's
    DECLARED (connector-stats) domain even when no runtime min/max was
    ever computed — simulated by checking declared_key_interval feeds
    the slot at registration."""
    from presto_tpu.exec.joinkeys import declared_key_interval

    s = _session(conn)
    plan = s.plan(QUERIES["q3"])

    def find_join(n):
        from presto_tpu.plan import nodes as N

        if isinstance(n, N.Join):
            return n
        for c in n.children:
            r = find_join(c)
            if r is not None:
                return r
        return None

    join = find_join(plan)
    iv = declared_key_interval(join.right, join.right_keys[0], s.catalog)
    assert iv is not None and iv[0] >= 0, (
        "TPC-H generator stats must bound the build key statically")
