"""Join operator tests (reference parity: TestHashJoinOperator with
RowPagesBuilder-style fixtures [SURVEY §4])."""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from presto_tpu.batch import Batch
from presto_tpu.exec.joins import BuildOutput, JoinBuildOperator, LookupJoinOperator
from presto_tpu.exec.operators import CapacityOverflow
from presto_tpu.exec.pipeline import BatchSource, Pipeline
from presto_tpu.expr import col
from presto_tpu.types import BIGINT, DOUBLE, INTEGER


def _batch(arrays, types, cap=None, valids=None):
    return Batch.from_numpy(arrays, types, capacity=cap, valids=valids)


def build_batch():
    return _batch(
        {"bk": np.array([1, 3, 5, 7], dtype=np.int64),
         "bval": np.array([10, 30, 50, 70], dtype=np.int64)},
        {"bk": BIGINT, "bval": BIGINT}, cap=8,
    )


def probe_batch():
    return _batch(
        {"pk": np.array([5, 2, 3, 7, 9, 1], dtype=np.int64),
         "pval": np.array([100, 200, 300, 400, 500, 600], dtype=np.int64)},
        {"pk": BIGINT, "pval": BIGINT}, cap=8,
    )


def run_join(join_type, unique=True, outputs=(BuildOutput("bval", "bval"),)):
    b = JoinBuildOperator(col("bk", BIGINT))
    Pipeline(BatchSource([build_batch()]), [b]).run()
    j = LookupJoinOperator(
        b, col("pk", BIGINT), outputs, join_type, unique=unique,
        out_capacity=None if unique or join_type in ("semi", "anti") else 32,
    )
    out = Pipeline(BatchSource([probe_batch()]), [j]).run()
    return pd.concat([o.to_pandas() for o in out])


def test_inner_unique():
    df = run_join("inner").sort_values("pk")
    assert df["pk"].tolist() == [1, 3, 5, 7]
    assert df["bval"].tolist() == [10, 30, 50, 70]
    assert df["pval"].tolist() == [600, 300, 100, 400]


def test_left_outer_unique():
    df = run_join("left").sort_values("pk")
    assert df["pk"].tolist() == [1, 2, 3, 5, 7, 9]
    vals = dict(zip(df["pk"], df["bval"]))
    assert vals[2] is None and vals[9] is None
    assert vals[3] == 30


def test_semi():
    df = run_join("semi", outputs=()).sort_values("pk")
    assert df["pk"].tolist() == [1, 3, 5, 7]
    assert list(df.columns) == ["pk", "pval"]


def test_anti():
    df = run_join("anti", outputs=()).sort_values("pk")
    assert df["pk"].tolist() == [2, 9]


def test_expansion_join_with_duplicates():
    bb = _batch(
        {"bk": np.array([1, 1, 2, 2, 2], dtype=np.int64),
         "bval": np.array([10, 11, 20, 21, 22], dtype=np.int64)},
        {"bk": BIGINT, "bval": BIGINT}, cap=8,
    )
    b = JoinBuildOperator(col("bk", BIGINT))
    Pipeline(BatchSource([bb]), [b]).run()
    j = LookupJoinOperator(
        b, col("pk", BIGINT), [BuildOutput("bval", "bval")], "inner",
        unique=False, out_capacity=32,
    )
    out = Pipeline(BatchSource([probe_batch()]), [j]).run()
    df = pd.concat([o.to_pandas() for o in out])
    left = probe_batch().to_pandas()
    right = bb.to_pandas()
    want = left.merge(right, left_on="pk", right_on="bk")
    got = df.sort_values(["pk", "bval"]).reset_index(drop=True)
    want = want.sort_values(["pk", "bval"]).reset_index(drop=True)
    assert got["pk"].tolist() == want["pk"].tolist()
    assert got["bval"].tolist() == want["bval"].tolist()
    assert got["pval"].tolist() == want["pval"].tolist()


def test_expansion_overflow_raises():
    bb = _batch(
        {"bk": np.zeros(8, dtype=np.int64), "bval": np.arange(8, dtype=np.int64)},
        {"bk": BIGINT, "bval": BIGINT},
    )
    pb = _batch(
        {"pk": np.zeros(8, dtype=np.int64), "pval": np.arange(8, dtype=np.int64)},
        {"pk": BIGINT, "pval": BIGINT},
    )
    b = JoinBuildOperator(col("bk", BIGINT))
    Pipeline(BatchSource([bb]), [b]).run()
    j = LookupJoinOperator(
        b, col("pk", BIGINT), [BuildOutput("bval", "bval")], "inner",
        unique=False, out_capacity=16,
    )
    with pytest.raises(CapacityOverflow):
        Pipeline(BatchSource([pb]), [j]).run()


def test_null_probe_keys_never_match():
    b = JoinBuildOperator(col("bk", BIGINT))
    Pipeline(BatchSource([build_batch()]), [b]).run()
    pb = _batch(
        {"pk": np.array([1, 3], dtype=np.int64), "pval": np.array([1, 2], dtype=np.int64)},
        {"pk": BIGINT, "pval": BIGINT},
        valids={"pk": np.array([True, False])},
    )
    j = LookupJoinOperator(b, col("pk", BIGINT), (), "inner")
    out = Pipeline(BatchSource([pb]), [j]).run()
    df = pd.concat([o.to_pandas() for o in out])
    assert df["pval"].tolist() == [1]
