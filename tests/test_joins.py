"""Join operator tests (reference parity: TestHashJoinOperator with
RowPagesBuilder-style fixtures [SURVEY §4])."""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from presto_tpu.batch import Batch
from presto_tpu.exec.joins import BuildOutput, JoinBuildOperator, LookupJoinOperator
from presto_tpu.exec.operators import CapacityOverflow
from presto_tpu.exec.pipeline import BatchSource, Pipeline
from presto_tpu.expr import col
from presto_tpu.types import BIGINT, DOUBLE, INTEGER


def _batch(arrays, types, cap=None, valids=None):
    return Batch.from_numpy(arrays, types, capacity=cap, valids=valids)


def build_batch():
    return _batch(
        {"bk": np.array([1, 3, 5, 7], dtype=np.int64),
         "bval": np.array([10, 30, 50, 70], dtype=np.int64)},
        {"bk": BIGINT, "bval": BIGINT}, cap=8,
    )


def probe_batch():
    return _batch(
        {"pk": np.array([5, 2, 3, 7, 9, 1], dtype=np.int64),
         "pval": np.array([100, 200, 300, 400, 500, 600], dtype=np.int64)},
        {"pk": BIGINT, "pval": BIGINT}, cap=8,
    )


def run_join(join_type, unique=True, outputs=(BuildOutput("bval", "bval"),)):
    b = JoinBuildOperator(col("bk", BIGINT))
    Pipeline(BatchSource([build_batch()]), [b]).run()
    j = LookupJoinOperator(
        b, col("pk", BIGINT), outputs, join_type, unique=unique,
        out_capacity=None if unique or join_type in ("semi", "anti") else 32,
    )
    out = Pipeline(BatchSource([probe_batch()]), [j]).run()
    return pd.concat([o.to_pandas() for o in out])


def test_inner_unique():
    df = run_join("inner").sort_values("pk")
    assert df["pk"].tolist() == [1, 3, 5, 7]
    assert df["bval"].tolist() == [10, 30, 50, 70]
    assert df["pval"].tolist() == [600, 300, 100, 400]


def test_left_outer_unique():
    df = run_join("left").sort_values("pk")
    assert df["pk"].tolist() == [1, 2, 3, 5, 7, 9]
    vals = dict(zip(df["pk"], df["bval"]))
    assert vals[2] is None and vals[9] is None
    assert vals[3] == 30


def test_semi():
    df = run_join("semi", outputs=()).sort_values("pk")
    assert df["pk"].tolist() == [1, 3, 5, 7]
    assert list(df.columns) == ["pk", "pval"]


def test_anti():
    df = run_join("anti", outputs=()).sort_values("pk")
    assert df["pk"].tolist() == [2, 9]


def test_expansion_join_with_duplicates():
    bb = _batch(
        {"bk": np.array([1, 1, 2, 2, 2], dtype=np.int64),
         "bval": np.array([10, 11, 20, 21, 22], dtype=np.int64)},
        {"bk": BIGINT, "bval": BIGINT}, cap=8,
    )
    b = JoinBuildOperator(col("bk", BIGINT))
    Pipeline(BatchSource([bb]), [b]).run()
    j = LookupJoinOperator(
        b, col("pk", BIGINT), [BuildOutput("bval", "bval")], "inner",
        unique=False, out_capacity=32,
    )
    out = Pipeline(BatchSource([probe_batch()]), [j]).run()
    df = pd.concat([o.to_pandas() for o in out])
    left = probe_batch().to_pandas()
    right = bb.to_pandas()
    want = left.merge(right, left_on="pk", right_on="bk")
    got = df.sort_values(["pk", "bval"]).reset_index(drop=True)
    want = want.sort_values(["pk", "bval"]).reset_index(drop=True)
    assert got["pk"].tolist() == want["pk"].tolist()
    assert got["bval"].tolist() == want["bval"].tolist()
    assert got["pval"].tolist() == want["pval"].tolist()


def test_expansion_overflow_raises():
    bb = _batch(
        {"bk": np.zeros(8, dtype=np.int64), "bval": np.arange(8, dtype=np.int64)},
        {"bk": BIGINT, "bval": BIGINT},
    )
    pb = _batch(
        {"pk": np.zeros(8, dtype=np.int64), "pval": np.arange(8, dtype=np.int64)},
        {"pk": BIGINT, "pval": BIGINT},
    )
    b = JoinBuildOperator(col("bk", BIGINT))
    Pipeline(BatchSource([bb]), [b]).run()
    j = LookupJoinOperator(
        b, col("pk", BIGINT), [BuildOutput("bval", "bval")], "inner",
        unique=False, out_capacity=16,
    )
    with pytest.raises(CapacityOverflow):
        Pipeline(BatchSource([pb]), [j]).run()


def test_null_probe_keys_never_match():
    b = JoinBuildOperator(col("bk", BIGINT))
    Pipeline(BatchSource([build_batch()]), [b]).run()
    pb = _batch(
        {"pk": np.array([1, 3], dtype=np.int64), "pval": np.array([1, 2], dtype=np.int64)},
        {"pk": BIGINT, "pval": BIGINT},
        valids={"pk": np.array([True, False])},
    )
    j = LookupJoinOperator(b, col("pk", BIGINT), (), "inner")
    out = Pipeline(BatchSource([pb]), [j]).run()
    df = pd.concat([o.to_pandas() for o in out])
    assert df["pval"].tolist() == [1]


# ---------------------------------------------------------------------------
# dense-domain direct lookup
# ---------------------------------------------------------------------------


def test_dense_probe_matches_sorted_probe(rng):
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.ops.join import (
        build_dense, build_lookup, probe_exists_dense, probe_unique,
        probe_unique_dense,
    )

    bcap, pcap, key_min, domain = 512, 2048, 100, 1500
    bkeys = rng.choice(np.arange(key_min, key_min + domain), 400, replace=False)
    bkeys = np.concatenate([bkeys, np.zeros(bcap - 400, np.int64)])
    blive = np.arange(bcap) < 400
    pkeys = rng.integers(key_min - 50, key_min + domain + 50, pcap)
    plive = rng.random(pcap) < 0.9

    dense = build_dense(jnp.asarray(bkeys), jnp.asarray(blive), key_min, domain)
    assert not bool(dense.overflow)
    sorted_side = build_lookup(jnp.asarray(bkeys), jnp.asarray(blive), bcap)
    got = probe_unique_dense(dense, jnp.asarray(pkeys), jnp.asarray(plive))
    want = probe_unique(sorted_side, jnp.asarray(pkeys), jnp.asarray(plive))
    np.testing.assert_array_equal(np.asarray(got.matched), np.asarray(want.matched))
    # matched rows must point at the same original build row
    m = np.asarray(got.matched)
    np.testing.assert_array_equal(
        np.asarray(got.build_row)[m], np.asarray(want.build_row)[m]
    )
    np.testing.assert_array_equal(
        np.asarray(probe_exists_dense(dense, jnp.asarray(pkeys), jnp.asarray(plive))),
        np.asarray(got.matched),
    )


def test_dense_build_flags_out_of_domain_keys():
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.ops.join import build_dense

    keys = jnp.asarray(np.array([5, 6, 99], np.int64))
    live = jnp.asarray(np.ones(3, bool))
    dense = build_dense(keys, live, 0, 10)  # 99 outside [0, 10)
    assert bool(dense.overflow)
    dead = build_dense(keys, jnp.asarray(np.array([True, True, False])), 0, 10)
    assert not bool(dead.overflow)


def test_sql_join_uses_dense_when_stats_bound_the_key():
    """The planner must pick the dense direct-address build for an
    FK->PK join whose build key has tight connector stats, and the
    result must match the sorted path exactly."""
    import pandas as pd

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.exec import joins as J
    from presto_tpu.runtime.session import Session

    # min(c_nationkey) keeps a build-side OUTPUT on the join: without
    # one, the leaf-route framework (ISSUE-9) folds the filter-only
    # unique join into a membership bitmap and no build ever runs
    q = ("select o_orderpriority, count(*) as n, min(c_nationkey) as mn "
         "from orders, customer "
         "where o_custkey = c_custkey and c_mktsegment = 'BUILDING' "
         "group by o_orderpriority order by o_orderpriority")
    s = Session({"tpch": TpchConnector(sf=0.01)})

    built_domains = []
    orig = J.JoinBuildOperator.__init__

    def spy(self, key, capacity=None, dense_domain=None, **kw):
        built_domains.append(dense_domain)
        orig(self, key, capacity, dense_domain, **kw)

    J.JoinBuildOperator.__init__ = spy
    try:
        got = s.sql(q)
    finally:
        J.JoinBuildOperator.__init__ = orig
    assert any(d is not None for d in built_domains), built_domains

    # same query with stats disabled -> sorted path; answers must agree
    import presto_tpu.exec.local_planner as LP

    orig_dd = LP.LocalExecutor.__dict__["_dense_domain"]  # keep staticmethod
    LP.LocalExecutor._dense_domain = staticmethod(lambda *a: None)
    try:
        want = Session({"tpch": TpchConnector(sf=0.01)}).sql(q)
    finally:
        LP.LocalExecutor._dense_domain = orig_dd
    pd.testing.assert_frame_equal(got, want)


# ---------------------------------------------------------------------------
# FULL OUTER JOIN (reference: LookupJoin unmatched-build emission half
# [SURVEY §2.1 operator row])
# ---------------------------------------------------------------------------


def _run_full(unique: bool, probe_batches=None):
    from presto_tpu.exec.joins import full_init_flags, full_tail

    b = JoinBuildOperator(col("bk", BIGINT))
    Pipeline(BatchSource([build_batch()]), [b]).run()
    outs = [BuildOutput("bval", "bval"), BuildOutput("bk", "bk")]
    j = LookupJoinOperator(
        b, col("pk", BIGINT), outs, "full", unique=unique,
        out_capacity=None if unique else 32,
    )
    flags = full_init_flags(b)
    rows = []
    schema = None
    for pb in (probe_batches or [probe_batch()]):
        out, flags = j.process_full(pb, flags)
        schema = pb
        rows.append(out)
    rows.append(full_tail(b, outs, flags, schema))
    recs = []
    for out in rows:
        live = np.asarray(out.live)
        cols = {n: (np.asarray(out[n].data), np.asarray(out[n].valid))
                for n in out.names}
        for i in np.nonzero(live)[0]:
            recs.append({
                n: (None if not v[i] else int(d[i]))
                for n, (d, v) in cols.items()
            })
    return recs


@pytest.mark.parametrize("unique", [True, False])
def test_full_outer_join(unique):
    recs = _run_full(unique)
    # probe keys [5,2,3,7,9,1]; build keys [1,3,5,7]: all four build
    # rows match -> probe-aligned rows plus NO tail rows
    got = sorted((r["pk"], r["bk"], r["bval"]) for r in recs)
    assert got == [
        (1, 1, 10), (2, None, None), (3, 3, 30),
        (5, 5, 50), (7, 7, 70), (9, None, None),
    ]


@pytest.mark.parametrize("unique", [True, False])
def test_full_outer_join_unmatched_build(unique):
    # probe only keys {3, 8}: build rows 1,5,7 are unmatched -> emitted
    # by the tail with NULL probe columns
    pb = _batch(
        {"pk": np.array([3, 8], dtype=np.int64),
         "pval": np.array([300, 800], dtype=np.int64)},
        {"pk": BIGINT, "pval": BIGINT}, cap=4,
    )
    recs = _run_full(unique, [pb])
    got = sorted(
        ((r["pk"] or -1), (r["bk"] or -1), (r["bval"] or -1)) for r in recs
    )
    assert got == [
        (-1, 1, 10), (-1, 5, 50), (-1, 7, 70), (3, 3, 30), (8, -1, -1),
    ]


def test_full_outer_multi_probe_batches_accumulate_flags():
    pb1 = _batch({"pk": np.array([1, 3], np.int64),
                  "pval": np.array([1, 3], np.int64)},
                 {"pk": BIGINT, "pval": BIGINT}, cap=4)
    pb2 = _batch({"pk": np.array([5, 4], np.int64),
                  "pval": np.array([5, 4], np.int64)},
                 {"pk": BIGINT, "pval": BIGINT}, cap=4)
    recs = _run_full(True, [pb1, pb2])
    # build key 7 is the only never-matched build row
    tails = [r for r in recs if r["pk"] is None]
    assert [(r["bk"], r["bval"]) for r in tails] == [(7, 70)]


def test_right_join_sql_matches_left_swapped():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runtime.session import Session

    s = Session({"tpch": TpchConnector(sf=0.01)})
    got = s.sql("select n_name, r_name from region right join nation "
                "on r_regionkey = n_nationkey order by n_name")
    want = s.sql("select n_name, r_name from nation left join region "
                 "on r_regionkey = n_nationkey order by n_name")
    pd.testing.assert_frame_equal(got, want)


def test_full_outer_sql_vs_pandas_oracle():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runtime.session import Session

    conn = TpchConnector(sf=0.01)
    s = Session({"tpch": conn})
    got = s.sql(
        "select r_regionkey, n_nationkey from region full outer join nation "
        "on r_regionkey = n_nationkey order by n_nationkey"
    )
    r = conn.table_pandas("region")[["r_regionkey"]]
    n = conn.table_pandas("nation")[["n_nationkey"]]
    want = r.merge(n, left_on="r_regionkey", right_on="n_nationkey",
                   how="outer").sort_values("n_nationkey")
    assert len(got) == len(want)
    np.testing.assert_array_equal(
        got["n_nationkey"].to_numpy(), want["n_nationkey"].to_numpy()
    )
    np.testing.assert_array_equal(
        got["r_regionkey"].isna().to_numpy(), want["r_regionkey"].isna().to_numpy()
    )


def test_packed_build_matches_unpacked(rng):
    """(key << bits | row) packed builds: one-gather probe must agree
    with the two-gather sorted path bit-for-bit, including dead rows,
    missing keys, and out-of-packable-range probe keys."""
    import jax.numpy as jnp

    from presto_tpu.ops.join import build_lookup, probe_unique

    bcap, pcap = 512, 2048
    bkeys = rng.choice(np.arange(0, 40_000), 400, replace=False)
    bkeys = np.concatenate([bkeys, np.zeros(bcap - 400, np.int64)])
    blive = np.arange(bcap) < 400
    pkeys = rng.integers(-100, 50_000, pcap)
    pkeys[:4] = [2**62, 2**62 - 1, -1, 0]  # unpackable / boundary probes
    plive = rng.random(pcap) < 0.9

    pb = int(bcap).bit_length()
    packed = build_lookup(jnp.asarray(bkeys), jnp.asarray(blive), bcap,
                          pack_bits=pb)
    plain = build_lookup(jnp.asarray(bkeys), jnp.asarray(blive), bcap)
    assert not bool(packed.sentinel_hit)
    got = probe_unique(packed, jnp.asarray(pkeys), jnp.asarray(plive),
                       pack_bits=pb)
    want = probe_unique(plain, jnp.asarray(pkeys), jnp.asarray(plive))
    np.testing.assert_array_equal(np.asarray(got.matched),
                                  np.asarray(want.matched))
    m = np.asarray(got.matched)
    np.testing.assert_array_equal(np.asarray(got.build_row)[m],
                                  np.asarray(want.build_row)[m])


def test_packed_build_flags_oversized_keys():
    import jax.numpy as jnp

    from presto_tpu.ops.join import build_lookup

    keys = jnp.asarray(np.array([1, 2, 2**61], np.int64))
    live = jnp.asarray(np.ones(3, bool))
    side = build_lookup(keys, live, 4, pack_bits=16)  # 2^61 needs >46 bits
    assert bool(side.sentinel_hit)


def test_sql_join_packed_path_fires_and_matches():
    """An FK->PK join with stats-bounded keys must take the packed
    build (pack_bits set) and produce identical results."""
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.exec import joins as J
    from presto_tpu.runtime.session import Session

    q = ("select n_name, count(*) as n from customer, nation "
         "where c_nationkey = n_nationkey group by n_name "
         "order by n_name")
    pack_seen = []
    orig = J.JoinBuildOperator.finish

    def spy(self):
        out = orig(self)
        pack_seen.append(self.pack_bits)
        return out

    J.JoinBuildOperator.finish = spy
    try:
        got = Session({"tpch": TpchConnector(sf=0.01)}).sql(q)
    finally:
        J.JoinBuildOperator.finish = orig
    assert any(p is not None for p in pack_seen), "packed build never used"
    conn = TpchConnector(sf=0.01)
    c, n = conn.table_pandas("customer"), conn.table_pandas("nation")
    want = (c.merge(n, left_on="c_nationkey", right_on="n_nationkey")
            .groupby("n_name", as_index=False).size()
            .rename(columns={"size": "n"}).sort_values("n_name"))
    assert got["n"].tolist() == want["n"].tolist()
