"""Differential suite for the leaf-fragment pattern framework + the
adaptive aggregation strategy (ISSUE-9, exec/leaf_route.py).

Contract under test: every ROUTED leaf fragment — TPC-H Q1 (the
hand-built specialization), TPC-H Q6 (keyless), the SSB Q1 flight
(membership join folded), and a CTAS-narrowed memory-connector GROUP BY
— is bit-identical to the generic operator route; routing is loud
(``exec.leaf_fused_route`` / ``exec.leaf_route_fallback.*`` counters);
violated advisory stats fall back, never mis-answer; and
``narrow_storage=0`` disables routing while preserving results (the
process-global env is restored, per the test_narrowing discipline).
"""

import os

import numpy as np
import pandas as pd
import pytest

from presto_tpu.connectors.ssb import SsbConnector
from presto_tpu.connectors.ssb.queries import QUERIES as SSB
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch.queries import QUERIES as TPCH
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

SF = 0.005


@pytest.fixture(autouse=True)
def narrow_env():
    """narrow_storage mirrors the process-global PRESTO_TPU_NARROW env
    var: restore it around every test (the repo convention)."""
    before = os.environ.get("PRESTO_TPU_NARROW")
    yield
    if before is None:
        os.environ.pop("PRESTO_TPU_NARROW", None)
    else:
        os.environ["PRESTO_TPU_NARROW"] = before


@pytest.fixture(scope="module")
def conns():
    return TpchConnector(sf=SF), SsbConnector(sf=SF)


def make_session(conns, **props):
    props.setdefault("result_cache_enabled", False)
    return Session({"tpch": conns[0], "ssb": conns[1]}, properties=props)


def snap(name: str) -> float:
    return REGISTRY.snapshot().get(name, 0.0)


ROUTED_QUERIES = {
    "q1": TPCH["q1"],
    "q6": TPCH["q6"],
    "ssb_q1_1": SSB["q1_1"],
    "ssb_q1_2": SSB["q1_2"],
    "ssb_q1_3": SSB["q1_3"],
}


@pytest.mark.parametrize("name", sorted(ROUTED_QUERIES))
def test_routed_vs_generic_bit_identical(conns, name):
    """The core differential: routed (narrow on) and generic
    (narrow_storage=0, which disables routing) runs return
    bit-identical frames, and the route counter proves the fused path
    actually fired — no silent de-routing."""
    q = ROUTED_QUERIES[name]
    s_on = make_session(conns)
    before = snap("exec.leaf_fused_route")
    got = s_on.sql(q)
    assert snap("exec.leaf_fused_route") == before + 1, \
        f"{name}: leaf fragment did not route"
    s_off = make_session(conns, narrow_storage=False)
    before_off = snap("exec.leaf_fused_route")
    want = s_off.sql(q)
    assert snap("exec.leaf_fused_route") == before_off, \
        f"{name}: narrow_storage=0 must disable routing"
    pd.testing.assert_frame_equal(got, want)


def test_ctas_memory_table_routes(conns):
    """The memory connector computes exact stats at store time, so a
    CTAS-narrowed table's GROUP BY leaf routes through the generalized
    kernel family — sum/count/min/max over a small int key domain."""
    s = make_session(conns)
    # integer columns: CTAS decodes decimals to DOUBLE (outside the
    # integer value grammar); ints round-trip with exact stats
    s.sql("create table leaf_t as select l_linenumber k, l_partkey v, "
          "l_suppkey p from lineitem")
    q = ("select k, sum(v) sv, count(*) c, min(p) mn, max(p) mx "
         "from leaf_t group by k order by k")
    before = snap("exec.leaf_fused_route")
    got = s.sql(q)
    assert snap("exec.leaf_fused_route") == before + 1
    s_off = Session({"memory": s.catalog.connector("memory")},
                    properties={"result_cache_enabled": False,
                                "narrow_storage": False})
    want = s_off.sql(q)
    pd.testing.assert_frame_equal(got, want)


def test_membership_empty_build(conns):
    """A filter-only join whose build side yields NO keys (impossible
    d_year) still routes and agrees with the generic route: empty
    bitmap, keyless sum over zero rows -> one NULL row."""
    q = SSB["q1_1"].replace("1993", "2099")
    s_on = make_session(conns)
    before = snap("exec.leaf_fused_route")
    got = s_on.sql(q)
    assert snap("exec.leaf_fused_route") == before + 1
    want = make_session(conns, narrow_storage=False).sql(q)
    pd.testing.assert_frame_equal(got, want)


def test_stats_violation_falls_back_loudly(conns):
    """Advisory stats that LIE (declared bounds tighter than the data)
    trip the kernel's runtime guard: the route falls back to the
    generic operators with a per-reason counter — a wrong answer is
    structurally impossible, only a wasted pass."""
    s = make_session(conns)
    want = s.sql(TPCH["q6"])
    catalog = s.catalog
    real_stats = catalog.stats

    def lying_stats(connector, table, column):
        st = real_stats(connector, table, column)
        if (table, column) == ("lineitem", "l_extendedprice"):
            import dataclasses

            # claim ep <= 1.00 (physical 100): real rows violate it
            return dataclasses.replace(st, max_value=1.0)
        return st

    catalog.stats = lying_stats
    try:
        before_fb = snap("exec.leaf_route_fallback")
        before_reason = snap("exec.leaf_route_fallback.value_overflow")
        before_route = snap("exec.leaf_fused_route")
        got = s.sql(TPCH["q6"])
    finally:
        catalog.stats = real_stats
    assert snap("exec.leaf_route_fallback") == before_fb + 1
    assert snap("exec.leaf_route_fallback.value_overflow") == \
        before_reason + 1
    assert snap("exec.leaf_fused_route") == before_route
    pd.testing.assert_frame_equal(got, want)


def test_membership_stats_violation_falls_back_loudly(conns):
    """Lying stats on the MEMBERSHIP key (declared max below real
    dates): a live probe row outside the declared domain has no bitmap
    slot but the generic join might match it, so the route must trip
    the runtime guard and fall back — silently dropping the row would
    be a wrong answer (revenue too small), not a wasted pass."""
    s = make_session(conns)
    want = s.sql(SSB["q1_1"])
    catalog = s.catalog
    real_stats = catalog.stats

    def lying_stats(connector, table, column):
        st = real_stats(connector, table, column)
        if (table, column) == ("lineorder", "lo_orderdate"):
            import dataclasses

            # claim the last order date is mid-1993: real rows (and
            # 1993 build keys the bitmap would need) lie beyond it
            return dataclasses.replace(st, max_value=19930601)
        return st

    catalog.stats = lying_stats
    try:
        before_reason = snap("exec.leaf_route_fallback.value_overflow")
        before_route = snap("exec.leaf_fused_route")
        got = s.sql(SSB["q1_1"])
    finally:
        catalog.stats = real_stats
    assert snap("exec.leaf_route_fallback.value_overflow") == \
        before_reason + 1
    assert snap("exec.leaf_fused_route") == before_route
    pd.testing.assert_frame_equal(got, want)


def test_null_bearing_ctas_column_never_routes_wrong(conns):
    """A CTAS column WITH NULLs: the memory connector's store-time
    stats now declare an honest null_fraction, so the fragment is
    inadmissible (stats reason) — and if stats LIE about NULL-freedom,
    the in-step null guard trips value_overflow. Either way the NULL
    semantics (count skips, min/sum ignore) come from the generic
    route, never a fused pass over NULL slots' fill values."""
    s = make_session(conns)
    s.sql("create table nullt as select l_linenumber k, case when "
          "l_linenumber = 1 then null else l_partkey end v from lineitem")
    q = ("select k, count(v) c, sum(v) sv, min(v) mn from nullt "
         "group by k order by k")
    before_route = snap("exec.leaf_fused_route")
    before_stats = snap("exec.leaf_route_fallback.stats")
    got = s.sql(q)
    assert snap("exec.leaf_fused_route") == before_route
    assert snap("exec.leaf_route_fallback.stats") == before_stats + 1

    # stats that LIE about NULL-freedom: runtime guard, loud fallback
    # (while narrowing is still on — a narrow-off comparison session
    # flips the process-global env, so it comes last)
    import dataclasses

    catalog = s.catalog
    real_stats = catalog.stats

    def lying(connector, table, column):
        st = real_stats(connector, table, column)
        if (table, column) == ("nullt", "v"):
            return dataclasses.replace(st, null_fraction=0.0)
        return st

    catalog.stats = lying
    try:
        before_ovf = snap("exec.leaf_route_fallback.value_overflow")
        before_route = snap("exec.leaf_fused_route")
        got2 = s.sql(q)
    finally:
        catalog.stats = real_stats
    assert snap("exec.leaf_route_fallback.value_overflow") == before_ovf + 1
    assert snap("exec.leaf_fused_route") == before_route

    s_off = Session({"memory": s.catalog.connector("memory")},
                    properties={"result_cache_enabled": False,
                                "narrow_storage": False})
    want = s_off.sql(q)
    pd.testing.assert_frame_equal(got, want)
    pd.testing.assert_frame_equal(got2, want)
    assert int(got[got.k == 1].c.iloc[0]) == 0  # count(v) skips NULLs


def test_out_of_int32_filter_literal_is_clamped(conns):
    """Filter literals past the int32 edge (the kernel casts bounds
    with np.int32): the spec clamps them exactly — an always-true
    bound routes and matches the generic rows, an unsatisfiable one
    routes to the empty aggregate."""
    s = make_session(conns)
    queries = ("select sum(l_quantity) s from lineitem "
               "where l_orderkey < 5000000000",
               "select sum(l_quantity) s from lineitem "
               "where l_orderkey > 5000000000")
    routed = {}
    for q in queries:
        before = snap("exec.leaf_fused_route")
        routed[q] = s.sql(q)
        assert snap("exec.leaf_fused_route") == before + 1, q
    # narrow-off comparison last: it flips the process-global env
    off = make_session(conns, narrow_storage=False)
    for q in queries:
        pd.testing.assert_frame_equal(routed[q], off.sql(q))


def test_inadmissible_leaf_counts_reason(conns):
    """Leaf-shaped fragments that fail admission are counted by
    reason: 'why didn't this route?' is answerable from metrics."""
    s = make_session(conns)
    # DOUBLE aggregate input: outside the integer value grammar
    before = snap("exec.leaf_route_fallback.value_shape")
    s.sql("select sum(l_quantity / 2) from lineitem "
          "where l_quantity < 10")
    assert snap("exec.leaf_route_fallback.value_shape") == before + 1
    # non-interval filter shape over a leaf
    before = snap("exec.leaf_route_fallback.filter_shape")
    s.sql("select sum(l_quantity) from lineitem "
          "where l_linenumber + l_linenumber < 4")
    assert snap("exec.leaf_route_fallback.filter_shape") == before + 1


def test_partial_agg_bypass_estimates_and_history(conns):
    """The adaptive bypass: a near-unique GROUP BY key (NDV ~ rows in
    the memory connector's exact stats) streams rows to one final pass
    — identical frames with the bypass on and off, strategy visible in
    EXPLAIN and counted; plan-stats history (runs >= 2) feeds the same
    decision on recurring fingerprints."""
    s = make_session(conns)
    s.sql("create table bypass_t as select l_orderkey * 10 + "
          "l_linenumber k, l_quantity v from lineitem")
    q = "select k, sum(v) sv, count(*) c from bypass_t group by k order by k"
    before = snap("agg.strategy.bypass")
    got = s.sql(q)
    assert snap("agg.strategy.bypass") == before + 1
    assert "agg_strategy=bypass" in s.explain(q)
    s_off = Session({"memory": s.catalog.connector("memory")},
                    properties={"result_cache_enabled": False,
                                "partial_agg_bypass": False})
    before_partial = snap("agg.strategy.partial")
    want = s_off.sql(q)
    assert snap("agg.strategy.partial") == before_partial + 1
    # EXPLAIN respects the property: the disabled session renders the
    # partial strategy its executor actually uses
    assert "agg_strategy=partial" in s_off.explain(q)
    pd.testing.assert_frame_equal(got, want)
    # history path: two tracked runs make the fingerprint recur, the
    # recorded actuals (groups ~ rows) land in the hints. ONE plan
    # object serves both the hints build and the lookup (hints key on
    # id(node)), and the estimate path is disabled so the history arm
    # ALONE must decide
    s.execute(q)
    s.execute(q)
    from unittest import mock

    from presto_tpu.exec import leaf_route
    from presto_tpu.plan import nodes as N

    plan = s.plan(q)
    hints = s._plan_hints(plan)
    assert hints, "recurring fingerprint produced no plan-stats hints"

    def find_agg(n):
        if isinstance(n, N.Aggregate):
            return n
        for c in n.children:
            r = find_agg(c)
            if r is not None:
                return r
        return None

    agg = find_agg(plan)
    assert id(agg) in hints, "hints did not map back onto the live plan"
    with mock.patch("presto_tpu.plan.bounds.estimate_groups",
                    return_value=None):
        assert leaf_route.bypass_partial_agg(agg, s.catalog, hints=hints), \
            "plan-stats history alone did not drive the bypass"
        assert not leaf_route.bypass_partial_agg(agg, s.catalog, hints={}), \
            "estimate path was not actually disabled"
    # the chosen strategy is recorded in system.plan_stats
    ps = s.sql("select node_type, strategy from plan_stats "
               "where strategy = 'bypass'")
    assert len(ps) >= 1


def test_low_cardinality_group_by_keeps_partial(conns):
    """A dictionary-domain GROUP BY (massive reduction) must never
    bypass: the direct-addressed fold is optimal."""
    s = make_session(conns)
    q = ("select l_returnflag, count(*) c from lineitem "
         "group by l_returnflag order by l_returnflag")
    assert "agg_strategy=" in s.explain(q)
    assert "agg_strategy=bypass" not in s.explain(q)


def test_explain_renders_strategies(conns):
    s = make_session(conns)
    assert "agg_strategy=fused" in s.explain(TPCH["q6"])
    assert "agg_strategy=fused" in s.explain(TPCH["q1"])
    assert "agg_strategy=fused" in s.explain(SSB["q1_1"])
    # a high-reduction int-key GROUP BY (NDV << rows) keeps partial
    q = "select o_orderdate, count(*) c from orders group by o_orderdate"
    assert "agg_strategy=partial" in s.explain(q)


def test_fragment_is_cached_zero_warm_retraces(conns):
    """Warm repeats of a routed query re-trace nothing (the fused step
    lives in the content-keyed executable cache)."""
    from presto_tpu.cache.exec_cache import trace_delta

    s = make_session(conns)
    s.sql(TPCH["q6"])
    with trace_delta() as td:
        s.sql(TPCH["q6"])
    assert td.traces == 0


@pytest.mark.slow
def test_distributed_leaf_route_matches_local(conns):
    """Distributed leaf route (shard_map fused step + psum): identical
    frames vs the local route for Q6, SSB Q1.1 (membership), and Q1."""
    from presto_tpu.parallel.mesh import make_mesh

    local = make_session(conns)
    dist = Session({"tpch": conns[0], "ssb": conns[1]},
                   mesh=make_mesh(8),
                   properties={"result_cache_enabled": False})
    for name in ("q6", "q1"):
        before = snap("exec.leaf_fused_route")
        got = dist.sql(TPCH[name])
        assert snap("exec.leaf_fused_route") == before + 1, name
        pd.testing.assert_frame_equal(got, local.sql(TPCH[name]))
    before = snap("exec.leaf_fused_route")
    got = dist.sql(SSB["q1_1"])
    assert snap("exec.leaf_fused_route") == before + 1
    pd.testing.assert_frame_equal(got, local.sql(SSB["q1_1"]))
    # min/max states must pmin/pmax across devices (a psum of
    # per-device min/max partials — identity fills included — is
    # garbage, not a reduction)
    dist.sql("create table dmm as select l_linenumber k, l_partkey v, "
             "l_suppkey p from lineitem")
    q = ("select k, sum(v) sv, count(*) c, min(p) mn, max(p) mx "
         "from dmm group by k order by k")
    before = snap("exec.leaf_fused_route")
    got = dist.sql(q)
    assert snap("exec.leaf_fused_route") == before + 1
    gen = Session({"memory": dist.catalog.connector("memory")},
                  properties={"result_cache_enabled": False,
                              "narrow_storage": False})
    pd.testing.assert_frame_equal(got, gen.sql(q))
