"""Query lifecycle hardening: error taxonomy, deadlines, admission
control, fragment retry, and distributed->local degradation — all
driven through the deterministic FaultInjector on the virtual CPU mesh.

Reference parity: QueryManager / SqlStageExecution treating failure as
a first-class state — typed error codes, query.max-run-time deadlines,
memory admission, task retry [SURVEY §3.1, §5.3]; validated here the
way the reference validates task failure handling: induced faults in a
fully in-process runner.
"""

import time

import numpy as np
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime import faults
from presto_tpu.runtime.errors import (
    ExceededTimeLimit,
    InternalError,
    PrestoError,
    ResourceExhausted,
    TransientFailure,
    UserError,
    error_code,
    is_retryable,
)
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

SF = 0.005
GROUPED_SQL = (
    "select l_orderkey, count(*) c, sum(l_quantity) q "
    "from lineitem group by l_orderkey"
)


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=SF)


@pytest.fixture()
def session(conn):
    return Session({"tpch": conn})


@pytest.fixture(scope="module")
def dist_session(conn):
    from presto_tpu.parallel.mesh import make_mesh

    return Session({"tpch": conn}, mesh=make_mesh(2),
                   properties={"retry_backoff_s": 0.0})


class Recorder:
    """Event listener capturing every lifecycle event."""

    def __init__(self):
        self.created, self.completed = [], []
        self.failed, self.retried = [], []

    def query_created(self, info):
        self.created.append(info)

    def query_completed(self, info):
        self.completed.append(info)

    def query_failed(self, info):
        self.failed.append(info)

    def fragment_retried(self, info):
        self.retried.append(info.fragment_retries)


def _counter(name):
    return REGISTRY.snapshot().get(name, 0.0)


# ---------------------------------------------------------------------------
# error taxonomy
# ---------------------------------------------------------------------------


def test_taxonomy_classes_and_stdlib_ancestry():
    # pre-taxonomy callers catch ValueError / RuntimeError; migration
    # must be additive
    assert issubclass(UserError, ValueError)
    for cls in (ResourceExhausted, ExceededTimeLimit, TransientFailure,
                InternalError):
        assert issubclass(cls, RuntimeError)
    assert is_retryable(TransientFailure("x"))
    assert not is_retryable(ResourceExhausted("x"))
    assert not is_retryable(UserError("x"))
    # per-instance override
    assert is_retryable(InternalError("x", retryable=True))
    assert error_code(TransientFailure("x")) == "TRANSIENT_FAILURE"
    assert error_code(NotImplementedError("x")) == "NOT_SUPPORTED"
    assert error_code(ValueError("x")) == "USER_ERROR"


def test_capacity_overflow_is_resource_exhausted():
    from presto_tpu.exec.operators import CapacityOverflow

    e = CapacityOverflow("Join", 1024)
    assert isinstance(e, ResourceExhausted)
    assert isinstance(e, PrestoError)
    assert not is_retryable(e)  # replaying hits the same capacity


def test_analysis_errors_are_user_errors(session):
    # raised before tracking starts (the REPL surface catches them);
    # the taxonomy still applies
    from presto_tpu.sql.analyzer import AnalysisError

    with pytest.raises(AnalysisError) as ei:
        session.sql("select no_such_column from nation")
    assert isinstance(ei.value, UserError)
    assert error_code(ei.value) == "USER_ERROR"


def test_user_errors_carry_code_on_query_info(session):
    rec = Recorder()
    session.add_event_listener(rec)
    with pytest.raises(UserError):
        # a RUNTIME user error (analysis passes; execution fails):
        # the scalar subquery yields one row per region
        session.sql("select (select r_regionkey from region) x from nation")
    info = session.query_history[-1]
    assert info.state == "FAILED"
    assert info.error_code == "USER_ERROR"
    assert info.retryable is False
    assert rec.failed and rec.failed[-1] is info
    assert rec.completed and rec.completed[-1] is info  # terminal event too


# ---------------------------------------------------------------------------
# fault injector
# ---------------------------------------------------------------------------


def test_fault_injector_times_and_prefix_matching():
    inj = faults.FaultInjector()
    inj.inject("exchange", times=2)
    with pytest.raises(TransientFailure):
        inj.check("exchange.join")
    with pytest.raises(TransientFailure):
        inj.check("exchange.aggregate")
    inj.check("exchange.join")  # exhausted: silent
    inj.check("scan")  # never armed
    assert inj.fired() == 2


def test_fault_injector_seeded_probability_is_deterministic():
    def fires(seed):
        inj = faults.FaultInjector(seed=seed)
        inj.inject("scan", times=None, probability=0.5)
        out = []
        for _ in range(32):
            try:
                inj.check("scan")
                out.append(0)
            except TransientFailure:
                out.append(1)
        return out

    assert fires(7) == fires(7)  # same seed, same sequence
    assert fires(7) != fires(8)  # seed matters
    assert 0 < sum(fires(7)) < 32


def test_fault_point_is_noop_without_injector():
    faults.fault_point("scan")  # must not raise


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------


def test_deadline_expiry_raises_exceeded_time_limit(session):
    session.set_property("query_max_run_time", 1e-9)
    before = _counter("query.deadline_exceeded")
    with pytest.raises(ExceededTimeLimit):
        session.sql(GROUPED_SQL)
    info = session.query_history[-1]
    assert info.state == "FAILED"
    assert info.error_code == "EXCEEDED_TIME_LIMIT"
    assert _counter("query.deadline_exceeded") > before
    # and NOT a generic failure: the error is typed, non-retryable
    assert info.retryable is False
    session.set_property("query_max_run_time", None)
    assert len(session.sql(GROUPED_SQL)) > 0  # no deadline: runs fine


def test_retry_backoff_never_sleeps_past_the_deadline(session):
    # the backoff sleep is capped by the REMAINING deadline, so a huge
    # retry_backoff_s cannot extend the query far past
    # query_max_run_time (expiry surfaces as ExceededTimeLimit, not as
    # the injected fault)
    session.set_property("query_max_run_time", 0.3)
    session.set_property("retry_count", 3)
    session.set_property("retry_backoff_s", 30.0)
    inj = faults.FaultInjector()
    inj.inject("aggregation", times=None)
    t0 = time.monotonic()
    try:
        with faults.injected(inj):
            with pytest.raises(ExceededTimeLimit):
                session.sql(GROUPED_SQL)
    finally:
        session.set_property("query_max_run_time", None)
    assert time.monotonic() - t0 < 5.0  # not 30s * attempts


def test_generous_deadline_does_not_fire(session):
    session.set_property("query_max_run_time", 3600.0)
    assert len(session.sql("select count(*) c from nation")) == 1


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_admission_rejects_before_execution(session):
    session.set_property("query_max_memory_bytes", 1)
    rec = Recorder()
    session.add_event_listener(rec)
    scans_before = _counter("query.admission_rejected")
    inj = faults.FaultInjector()
    inj.inject("scan", times=None)  # any scan attempt would raise
    with faults.injected(inj):
        with pytest.raises(ResourceExhausted, match="admission control"):
            session.sql(GROUPED_SQL)
    assert inj.fired() == 0  # rejected BEFORE launch: no scan ran
    assert _counter("query.admission_rejected") > scans_before
    info = session.query_history[-1]
    assert info.error_code == "RESOURCE_EXHAUSTED"
    assert rec.failed


def test_admission_default_is_permissive(session):
    assert session.prop("query_max_memory_bytes") is None
    assert len(session.sql(GROUPED_SQL)) > 0


# ---------------------------------------------------------------------------
# fragment retry (local tier: eager aggregation dispatch)
# ---------------------------------------------------------------------------


def test_retry_until_success_local(session):
    session.set_property("retry_count", 3)
    session.set_property("retry_backoff_s", 0.0)
    rec = Recorder()
    session.add_event_listener(rec)
    before = _counter("fragment.retried")
    inj = faults.FaultInjector()
    inj.inject("aggregation", times=2)
    with faults.injected(inj):
        df = session.sql(GROUPED_SQL)
    assert len(df) > 0
    assert inj.fired() == 2
    info = session.query_history[-1]
    assert info.state == "FINISHED"
    # the retry count is visible in the metrics snapshot AND on the
    # QueryInfo delivered to query_completed
    assert _counter("fragment.retried") == before + 2
    assert rec.completed[-1].fragment_retries == 2
    assert rec.retried == [1, 2]


def test_retry_streaming_only_query(session):
    # a plan with NO pipeline breaker drains its lazy scan stream at
    # the sink, so the sink drain must be a retry boundary too —
    # otherwise retry behavior would depend invisibly on query shape
    session.set_property("retry_count", 2)
    session.set_property("retry_backoff_s", 0.0)
    inj = faults.FaultInjector()
    inj.inject("scan", times=1)
    with faults.injected(inj):
        df = session.sql("select n_name from nation")
    assert len(df) == 25
    info = session.query_history[-1]
    assert info.state == "FINISHED"
    assert info.fragment_retries == 1


def test_retry_exhaustion_raises_the_fault(session):
    session.set_property("retry_count", 1)
    session.set_property("retry_backoff_s", 0.0)
    inj = faults.FaultInjector()
    inj.inject("aggregation", times=None)  # never stops failing
    with faults.injected(inj):
        with pytest.raises(TransientFailure):
            session.sql(GROUPED_SQL)
    info = session.query_history[-1]
    assert info.state == "FAILED"
    assert info.error_code == "TRANSIENT_FAILURE"
    assert info.retryable is True
    assert info.fragment_retries == 1
    # exhaustion is tagged: ancestors must not multiply the budget, so
    # total fires = 1 initial + retry_count
    assert inj.fired() == 2


def test_non_retryable_faults_are_not_retried(session):
    session.set_property("retry_count", 5)
    inj = faults.FaultInjector()
    inj.inject("aggregation", error=ResourceExhausted, times=None)
    with faults.injected(inj):
        with pytest.raises(ResourceExhausted):
            session.sql(GROUPED_SQL)
    assert inj.fired() == 1  # no retry burned on a deterministic wall


def test_query_level_retries_still_rerun_anything(session):
    # the pre-taxonomy knob keeps its semantics: ANY failure re-runs
    session.set_property("query_retries", 2)
    session.set_property("retry_count", 0)
    inj = faults.FaultInjector()
    inj.inject("aggregation", error=ResourceExhausted, times=2)
    with faults.injected(inj):
        df = session.sql(GROUPED_SQL)
    assert len(df) > 0
    assert inj.fired() == 2


# ---------------------------------------------------------------------------
# distributed tier: exchange faults, retry, degradation
# ---------------------------------------------------------------------------


def test_exchange_fault_survived_by_fragment_retry(dist_session):
    dist_session.set_property("retry_count", 2)
    rec = Recorder()
    dist_session.add_event_listener(rec)
    before = _counter("fragment.retried")
    inj = faults.FaultInjector()
    inj.inject("exchange.aggregate", times=1)
    with faults.injected(inj):
        df = dist_session.sql(GROUPED_SQL)
    info = dist_session.query_history[-1]
    assert info.state == "FINISHED"
    assert not info.degraded  # survived ON the mesh
    assert inj.fired() == 1
    assert info.fragment_retries == 1
    assert _counter("fragment.retried") == before + 1
    assert rec.completed[-1].fragment_retries == 1
    assert int(df["c"].sum()) == int(
        dist_session.sql("select count(*) c from lineitem")["c"][0])


def test_distributed_degrades_to_local_pipeline(dist_session):
    dist_session.set_property("retry_count", 1)
    dist_session.set_property("degrade_to_local", True)
    before = _counter("query.degraded_to_local")
    inj = faults.FaultInjector()
    inj.inject("exchange.aggregate", times=None)  # the mesh never works
    with faults.injected(inj):
        df = dist_session.sql(GROUPED_SQL)
    info = dist_session.query_history[-1]
    assert info.state == "FINISHED"
    assert info.degraded
    assert _counter("query.degraded_to_local") == before + 1
    # correct answer from the local pipeline (no exchange hook points)
    assert len(df) > 0


def test_degraded_stats_do_not_double_count(dist_session):
    # the failed distributed attempt's node stats must not leak into
    # the degraded run's QueryInfo (same invariant query-level retries
    # keep by using a fresh recorder per attempt)
    dist_session.set_property("retry_count", 0)
    _df, clean = dist_session.execute(GROUPED_SQL)  # fault-free baseline

    def scan_stats(info):
        return [(s["invocations"], s["output_rows"])
                for s in info.node_stats if s["node"] == "TableScan"]

    inj = faults.FaultInjector()
    inj.inject("exchange.aggregate", times=None)
    with faults.injected(inj):
        _df, info = dist_session.execute(GROUPED_SQL)
    assert info.degraded and not clean.degraded
    assert scan_stats(info)
    # identical to a clean local run: nothing from the failed
    # distributed attempt summed in
    assert scan_stats(info) == [(1, r) for _, r in scan_stats(clean)]


def test_degradation_disabled_raises_typed_failure(dist_session):
    dist_session.set_property("retry_count", 1)
    dist_session.set_property("degrade_to_local", False)
    inj = faults.FaultInjector()
    inj.inject("exchange.aggregate", times=None)
    try:
        with faults.injected(inj):
            with pytest.raises(TransientFailure):
                dist_session.sql(GROUPED_SQL)
    finally:
        dist_session.set_property("degrade_to_local", True)
    info = dist_session.query_history[-1]
    assert info.state == "FAILED"
    assert info.error_code == "TRANSIENT_FAILURE"
    assert info.fragment_retries == 1


def test_scan_fault_on_distributed_tier_retries(dist_session):
    dist_session.set_property("retry_count", 2)
    inj = faults.FaultInjector()
    inj.inject("scan", times=1)
    with faults.injected(inj):
        df = dist_session.sql("select count(*) c from nation")
    assert int(df["c"][0]) == 25
    assert dist_session.query_history[-1].fragment_retries == 1


# ---------------------------------------------------------------------------
# QueryInfo JSON surface
# ---------------------------------------------------------------------------


def test_query_info_json_has_lifecycle_fields(session):
    import json

    session.sql("select count(*) c from nation")
    d = json.loads(session.query_history[-1].to_json())
    for key in ("errorCode", "retryable", "fragmentRetries", "degraded"):
        assert key in d
    assert d["fragmentRetries"] == 0
    assert d["degraded"] is False
