"""Memory connector + write path (CREATE TABLE AS / INSERT / DROP).

Reference parity: presto-memory (MemoryPagesStore) and the
ConnectorPageSink write half of the SPI, with all-or-nothing statement
visibility [SURVEY §2.1 SPI row, §2.2, §5.4]."""

import numpy as np
import pandas as pd
import pytest

from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.session import Session


@pytest.fixture()
def session():
    return Session({"tpch": TpchConnector(sf=0.01)})


def test_ctas_roundtrip_and_join_back(session):
    r = session.sql(
        "create table flag_counts as select l_returnflag f, count(*) c "
        "from lineitem group by l_returnflag"
    )
    assert int(r["rows"][0]) == 3
    df = session.sql("select f, c from flag_counts order by f")
    li = session.catalog.connector("tpch").table_pandas("lineitem")
    want = li.groupby("l_returnflag").size()
    assert df["f"].tolist() == list(want.index)
    assert df["c"].tolist() == want.tolist()
    # created tables join back against base tables
    df2 = session.sql(
        "select f, c from flag_counts where c > 0 order by c desc limit 1"
    )
    assert int(df2["c"][0]) == int(want.max())


def test_insert_appends_atomically(session):
    session.sql("create table t as select 1 a, 2 b")
    session.sql("insert into t select 3 a, 4 b")
    df = session.sql("select a, b from t order by a")
    assert df["a"].tolist() == [1, 3]
    # schema mismatch refuses without corrupting the table
    with pytest.raises(Exception, match="schema"):
        session.sql("insert into t select 5 a")
    assert len(session.sql("select * from t")) == 2


def test_drop_table(session):
    session.sql("create table gone as select 1 x")
    session.sql("drop table gone")
    with pytest.raises(Exception):
        session.sql("select * from gone")
    session.sql("drop table if exists gone")  # no error
    with pytest.raises(ValueError, match="not found"):
        session.sql("drop table gone")


def test_ctas_rejects_existing(session):
    session.sql("create table dup as select 1 x")
    with pytest.raises(ValueError, match="already exists"):
        session.sql("create table dup as select 2 x")


def test_nulls_and_strings_roundtrip():
    conn = MemoryConnector()
    df = pd.DataFrame({
        "k": [1, 2, 3],
        "s": ["apple", None, "banana"],
        "v": [1.5, np.nan, 2.5],
        "n": pd.array([10, None, 30], dtype="Int64"),
    })
    conn.create_table("t", df)
    out = conn.table_pandas("t")
    assert out["k"].tolist() == [1, 2, 3]
    assert out["s"].tolist()[0] == "apple" and out["s"].tolist()[2] == "banana"
    assert out["s"][1] is None or pd.isna(out["s"][1])
    assert pd.isna(out["v"][1])
    # nullable int survives as integer (not float)
    assert int(out["n"][0]) == 10 and int(out["n"][2]) == 30
    # NULL semantics through SQL: count skips them
    s = Session({"mem": conn})
    got = s.sql("select count(*) n, count(s) ns, count(n) nn from t")
    assert got.iloc[0].tolist() == [3, 2, 2]


def test_created_table_queryable_distributed():
    from presto_tpu.parallel.mesh import make_mesh

    s = Session({"tpch": TpchConnector(sf=0.01)}, mesh=make_mesh(8))
    s.sql(
        "create table per_supp as select l_suppkey k, sum(l_quantity) q "
        "from lineitem group by l_suppkey"
    )
    df = s.sql("select count(*) n, sum(q) tq from per_supp")
    li = s.catalog.connector("tpch").table_pandas("lineitem")
    assert int(df["n"][0]) == li["l_suppkey"].nunique()
    np.testing.assert_allclose(
        float(df["tq"][0]), float(li["l_quantity"].sum()), rtol=1e-9
    )


def test_ddl_cannot_shadow_other_catalogs(session):
    """Name resolution prefers user connectors, so a memory table
    shadowed by a read-only catalog would be unreachable — DDL must
    reject the collision up front (before running the query)."""
    with pytest.raises(ValueError, match="already exists"):
        session.sql("create table nation as select 1 x")
    with pytest.raises(ValueError, match="read-only"):
        session.sql("insert into lineitem select 1 a")
    with pytest.raises(ValueError, match="read-only"):
        session.sql("drop table nation")


def test_fromless_select_and_string_literals(session):
    df = session.sql("select 'hello' z, 1 + 1 n")
    assert df["z"][0] == "hello" and int(df["n"][0]) == 2
    df2 = session.sql("select 'tag' t, n_name from nation order by n_name limit 2")
    assert df2["t"].tolist() == ["tag", "tag"]


def test_insert_type_and_existence_guards(session):
    session.sql("create table typed as select 1 a, 2.5 x")
    # double column stays double (no integral-float reclassification)
    df = session.sql("select x from typed")
    assert abs(float(df["x"][0]) - 2.5) < 1e-9
    # type-family mismatch rejected, table unchanged
    with pytest.raises(Exception, match="type mismatch"):
        session.sql("insert into typed select 'str' a, 1.0 x")
    assert int(session.sql("select a from typed")["a"][0]) == 1
    # INSERT into a nonexistent table errors instead of creating it
    with pytest.raises(ValueError, match="not found"):
        session.sql("insert into never_created select 1 z")


def test_double_stays_double_across_inserts():
    conn = MemoryConnector()
    conn.create_table("d", pd.DataFrame({"x": [2.0, 4.0]}))
    from presto_tpu.types import TypeKind

    assert conn.schema("d")["x"].kind is TypeKind.DOUBLE
    conn.insert("d", pd.DataFrame({"x": [1.5]}))
    assert conn.schema("d")["x"].kind is TypeKind.DOUBLE
    assert conn.table_pandas("d")["x"].tolist() == [2.0, 4.0, 1.5]
