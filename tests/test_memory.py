"""Memory connector + write path (CREATE TABLE AS / INSERT / DROP),
and the shared MemoryPool's accounting invariants.

Reference parity: presto-memory (MemoryPagesStore) and the
ConnectorPageSink write half of the SPI, with all-or-nothing statement
visibility [SURVEY §2.1 SPI row, §2.2, §5.4]; MemoryPool/QueryContext
reservation accounting [SURVEY §2.1 L9]."""

import threading
import time

import numpy as np
import pandas as pd
import pytest

from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.errors import ResourceExhausted
from presto_tpu.runtime.memory import MemoryPool, device_budget_bytes
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session


@pytest.fixture()
def session():
    return Session({"tpch": TpchConnector(sf=0.01)})


def test_ctas_roundtrip_and_join_back(session):
    r = session.sql(
        "create table flag_counts as select l_returnflag f, count(*) c "
        "from lineitem group by l_returnflag"
    )
    assert int(r["rows"][0]) == 3
    df = session.sql("select f, c from flag_counts order by f")
    li = session.catalog.connector("tpch").table_pandas("lineitem")
    want = li.groupby("l_returnflag").size()
    assert df["f"].tolist() == list(want.index)
    assert df["c"].tolist() == want.tolist()
    # created tables join back against base tables
    df2 = session.sql(
        "select f, c from flag_counts where c > 0 order by c desc limit 1"
    )
    assert int(df2["c"][0]) == int(want.max())


def test_insert_appends_atomically(session):
    session.sql("create table t as select 1 a, 2 b")
    session.sql("insert into t select 3 a, 4 b")
    df = session.sql("select a, b from t order by a")
    assert df["a"].tolist() == [1, 3]
    # schema mismatch refuses without corrupting the table
    with pytest.raises(Exception, match="schema"):
        session.sql("insert into t select 5 a")
    assert len(session.sql("select * from t")) == 2


def test_drop_table(session):
    session.sql("create table gone as select 1 x")
    session.sql("drop table gone")
    with pytest.raises(Exception):
        session.sql("select * from gone")
    session.sql("drop table if exists gone")  # no error
    with pytest.raises(ValueError, match="not found"):
        session.sql("drop table gone")


def test_ctas_rejects_existing(session):
    session.sql("create table dup as select 1 x")
    with pytest.raises(ValueError, match="already exists"):
        session.sql("create table dup as select 2 x")


def test_nulls_and_strings_roundtrip():
    conn = MemoryConnector()
    df = pd.DataFrame({
        "k": [1, 2, 3],
        "s": ["apple", None, "banana"],
        "v": [1.5, np.nan, 2.5],
        "n": pd.array([10, None, 30], dtype="Int64"),
    })
    conn.create_table("t", df)
    out = conn.table_pandas("t")
    assert out["k"].tolist() == [1, 2, 3]
    assert out["s"].tolist()[0] == "apple" and out["s"].tolist()[2] == "banana"
    assert out["s"][1] is None or pd.isna(out["s"][1])
    assert pd.isna(out["v"][1])
    # nullable int survives as integer (not float)
    assert int(out["n"][0]) == 10 and int(out["n"][2]) == 30
    # NULL semantics through SQL: count skips them
    s = Session({"mem": conn})
    got = s.sql("select count(*) n, count(s) ns, count(n) nn from t")
    assert got.iloc[0].tolist() == [3, 2, 2]


def test_created_table_queryable_distributed():
    from presto_tpu.parallel.mesh import make_mesh

    s = Session({"tpch": TpchConnector(sf=0.01)}, mesh=make_mesh(8))
    s.sql(
        "create table per_supp as select l_suppkey k, sum(l_quantity) q "
        "from lineitem group by l_suppkey"
    )
    df = s.sql("select count(*) n, sum(q) tq from per_supp")
    li = s.catalog.connector("tpch").table_pandas("lineitem")
    assert int(df["n"][0]) == li["l_suppkey"].nunique()
    np.testing.assert_allclose(
        float(df["tq"][0]), float(li["l_quantity"].sum()), rtol=1e-9
    )


def test_ddl_cannot_shadow_other_catalogs(session):
    """Name resolution prefers user connectors, so a memory table
    shadowed by a read-only catalog would be unreachable — DDL must
    reject the collision up front (before running the query)."""
    with pytest.raises(ValueError, match="already exists"):
        session.sql("create table nation as select 1 x")
    with pytest.raises(ValueError, match="read-only"):
        session.sql("insert into lineitem select 1 a")
    with pytest.raises(ValueError, match="read-only"):
        session.sql("drop table nation")


def test_fromless_select_and_string_literals(session):
    df = session.sql("select 'hello' z, 1 + 1 n")
    assert df["z"][0] == "hello" and int(df["n"][0]) == 2
    df2 = session.sql("select 'tag' t, n_name from nation order by n_name limit 2")
    assert df2["t"].tolist() == ["tag", "tag"]


def test_insert_type_and_existence_guards(session):
    session.sql("create table typed as select 1 a, 2.5 x")
    # double column stays double (no integral-float reclassification)
    df = session.sql("select x from typed")
    assert abs(float(df["x"][0]) - 2.5) < 1e-9
    # type-family mismatch rejected, table unchanged
    with pytest.raises(Exception, match="type mismatch"):
        session.sql("insert into typed select 'str' a, 1.0 x")
    assert int(session.sql("select a from typed")["a"][0]) == 1
    # INSERT into a nonexistent table errors instead of creating it
    with pytest.raises(ValueError, match="not found"):
        session.sql("insert into never_created select 1 z")


def test_double_stays_double_across_inserts():
    conn = MemoryConnector()
    conn.create_table("d", pd.DataFrame({"x": [2.0, 4.0]}))
    from presto_tpu.types import TypeKind

    assert conn.schema("d")["x"].kind is TypeKind.DOUBLE
    conn.insert("d", pd.DataFrame({"x": [1.5]}))
    assert conn.schema("d")["x"].kind is TypeKind.DOUBLE
    assert conn.table_pandas("d")["x"].tolist() == [2.0, 4.0, 1.5]


# ---------------------------------------------------------------------------
# device budget (warm-process correction)
# ---------------------------------------------------------------------------


class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_device_budget_subtracts_bytes_in_use():
    cold = device_budget_bytes(
        _FakeDevice({"bytes_limit": 16 << 30, "bytes_in_use": 0})
    )
    warm = device_budget_bytes(
        _FakeDevice({"bytes_limit": 16 << 30, "bytes_in_use": 2 << 30})
    )
    assert cold == 8 << 30
    assert warm == cold - (2 << 30)  # a warm process must not over-admit
    # a nearly-full allocator still leaves the floor, not zero/negative
    full = device_budget_bytes(
        _FakeDevice({"bytes_limit": 16 << 30, "bytes_in_use": 15 << 30})
    )
    assert full == 256 << 20


def test_device_budget_fallbacks():
    from presto_tpu.runtime.memory import DEFAULT_BUDGET_BYTES

    class NoStats:
        def memory_stats(self):
            raise RuntimeError("unavailable")

    assert device_budget_bytes(NoStats()) == DEFAULT_BUDGET_BYTES
    assert device_budget_bytes(_FakeDevice(None)) == DEFAULT_BUDGET_BYTES


# ---------------------------------------------------------------------------
# MemoryPool accounting invariants
# ---------------------------------------------------------------------------


def _counter(name):
    return REGISTRY.snapshot().get(name, 0.0)


def test_pool_reserve_release_balance():
    pool = MemoryPool(1000)
    assert pool.reserve("q1", 400) >= 0.0
    pool.reserve("q2", 600)
    assert pool.reserved_bytes == 1000 and pool.free_bytes == 0
    assert pool.reservations() == {"q1": 400, "q2": 600}
    assert pool.release("q1") == 400
    assert pool.release("q1") == 0  # idempotent
    assert pool.reserved_bytes == 600
    pool.release("q2")
    assert pool.reserved_bytes == 0 and pool.active_count == 0


def test_pool_over_capacity_rejected_immediately_with_detail():
    pool = MemoryPool(1000)
    t0 = time.monotonic()
    with pytest.raises(ResourceExhausted) as ei:
        pool.reserve("big", 2000, timeout_s=60.0, detail="peak at Join")
    assert time.monotonic() - t0 < 1.0  # can NEVER fit: no queueing
    msg = str(ei.value)
    assert "2000" in msg and "1000" in msg and "peak at Join" in msg
    assert pool.reserved_bytes == 0


def test_pool_timeout_raises_typed_with_pool_state():
    pool = MemoryPool(1000)
    pool.reserve("holder", 900)
    before = _counter("memory.queue_timeouts")
    with pytest.raises(ResourceExhausted) as ei:
        pool.reserve("waiter", 500, timeout_s=0.05,
                     detail="peak estimate 500 bytes at Aggregate")
    msg = str(ei.value)
    # estimate, capacity, and live reservations all surface
    assert "500" in msg and "900/1000" in msg and "Aggregate" in msg
    assert _counter("memory.queue_timeouts") == before + 1
    pool.release("holder")
    assert pool.reserved_bytes == 0


def test_pool_fifo_blocks_then_runs():
    pool = MemoryPool(1000)
    pool.reserve("blocker", 1000)
    got = []

    def waiter():
        pool.reserve("late", 800, timeout_s=30.0)
        got.append(pool.reservations())
        pool.release("late")

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    deadline = time.monotonic() + 5.0
    while pool.queued_count == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert pool.queued_count == 1  # queued, not failed
    pool.release("blocker")
    t.join(timeout=10.0)
    assert not t.is_alive()
    assert got and got[0] == {"late": 800}
    assert pool.reserved_bytes == 0


def test_pool_fifo_no_starvation_head_of_line():
    """A large reservation at the head must not be starved by small
    ones arriving behind it (strict FIFO grants)."""
    pool = MemoryPool(1000)
    pool.reserve("holder", 600)
    order = []

    def want(qid, n):
        pool.reserve(qid, n, timeout_s=30.0)
        order.append(qid)

    big = threading.Thread(target=want, args=("big", 900), daemon=True)
    big.start()
    deadline = time.monotonic() + 5.0
    while pool.queued_count < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    small = threading.Thread(target=want, args=("small", 100), daemon=True)
    small.start()
    # "small" COULD fit right now (600+100 <= 1000) but "big" is ahead
    time.sleep(0.1)
    assert order == []
    pool.release("holder")
    big.join(timeout=10.0)
    pool.release("big")
    small.join(timeout=10.0)
    assert order == ["big", "small"]
    pool.release("small")
    assert pool.reserved_bytes == 0


# ---------------------------------------------------------------------------
# reservation/release balance across every query terminal state
# ---------------------------------------------------------------------------


@pytest.fixture()
def pooled_session():
    pool = MemoryPool(device_budget_bytes() * 64, name="test")
    s = Session({"tpch": TpchConnector(sf=0.005)}, memory_pool=pool,
                properties={"retry_backoff_s": 0.0})
    return s, pool


def test_pool_balance_success_path(pooled_session):
    s, pool = pooled_session
    before = _counter("memory.reserved")
    s.sql("select count(*) c from nation")
    assert _counter("memory.reserved") == before + 1
    assert pool.reserved_bytes == 0 and pool.active_count == 0
    assert s.query_history[-1].memory_reserved_bytes > 0


def test_pool_balance_user_error_path(pooled_session):
    s, pool = pooled_session
    with pytest.raises(ValueError):
        # runtime user error: scalar subquery yields a row per region
        s.sql("select (select r_regionkey from region) x from nation")
    assert pool.reserved_bytes == 0 and pool.active_count == 0


def test_pool_balance_deadline_path(pooled_session):
    s, pool = pooled_session
    s.set_property("query_max_run_time", 1e-9)
    with pytest.raises(RuntimeError):
        s.sql("select count(*) c from lineitem")
    assert pool.reserved_bytes == 0 and pool.active_count == 0


def test_pool_balance_fault_path(pooled_session):
    from presto_tpu.runtime import faults

    s, pool = pooled_session
    inj = faults.FaultInjector()
    inj.inject("scan", times=None)
    with faults.injected(inj):
        with pytest.raises(RuntimeError):
            s.sql("select count(*) c from nation")
    assert inj.fired() > 0
    assert pool.reserved_bytes == 0 and pool.active_count == 0


def test_pool_balance_cache_hit_path(pooled_session):
    s, pool = pooled_session
    q = "select n_regionkey k, count(*) c from nation group by n_regionkey"
    s.sql(q)
    before = _counter("memory.reserved")
    s.sql(q)  # result-cache hit: no execution, no reservation taken
    assert s.query_history[-1].cache_hit
    assert _counter("memory.reserved") == before
    assert pool.reserved_bytes == 0 and pool.active_count == 0


def test_sessions_share_explicit_pool_and_serialize():
    """Two sessions over one pool: when the pool can only hold one
    query's reservation, the second QUEUES and then runs — nobody
    fails (block-then-run admission)."""
    q = "select count(*) c from nation"
    conn = TpchConnector(sf=0.005)
    probe = Session({"tpch": conn})
    probe.sql(q)
    peak = probe.query_history[-1].memory_reserved_bytes
    assert peak > 0
    pool = MemoryPool(int(peak * 1.5), name="shared")  # one at a time
    pool.reserve("outsider", peak)  # congestion both sessions see
    results, errors = [], []

    def run():
        try:
            s = Session({"tpch": conn}, memory_pool=pool,
                        properties={"admission_queue_timeout_s": 60.0})
            results.append(int(s.sql(q)["c"][0]))
            info = s.query_history[-1]
            # time blocked on the pool is QUEUED time in the phase
            # breakdown, not execution time
            assert info.memory_queued_s > 0.0
            if info.queued_s + 1e-3 < info.memory_queued_s:
                errors.append(
                    f"queued_s {info.queued_s} hides pool wait "
                    f"{info.memory_queued_s}"
                )
        except Exception as e:  # noqa: BLE001 — asserted empty below
            errors.append(e)

    threads = [threading.Thread(target=run, daemon=True) for _ in range(2)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 10.0
    while pool.queued_count < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert pool.queued_count == 2  # both queued on memory, neither failed
    pool.release("outsider")
    for t in threads:
        t.join(timeout=120.0)
        assert not t.is_alive(), "query hung in the admission queue"
    assert errors == []
    assert results == [25, 25]
    assert pool.reserved_bytes == 0 and pool.active_count == 0
