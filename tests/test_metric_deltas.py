"""Per-query metric attribution (ISSUE-8): QueryMetricsDelta capture
at the run_plan choke point, no cross-query bleed under concurrency on
the ONE process-global registry, derived query_history columns, and
the OpenMetrics text exposition.
"""

import json
import re
import threading

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.metrics import (
    REGISTRY,
    MetricsRegistry,
    QueryMetricsDelta,
    install_delta,
    to_openmetrics,
    uninstall_delta,
)
from presto_tpu.runtime.session import Session
from presto_tpu.runtime.stats import QueryInfo

Q3 = None  # resolved lazily from the TPC-H query set


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=0.005)


def _q3():
    global Q3
    if Q3 is None:
        from presto_tpu.connectors.tpch.queries import QUERIES

        Q3 = QUERIES["q3"]
    return Q3


# ---------------------------------------------------------------------------
# delta collector mechanics
# ---------------------------------------------------------------------------


def test_delta_captures_adds_only_while_installed():
    reg = MetricsRegistry()
    d = QueryMetricsDelta()
    reg.counter("x.hits").add(2.0)  # before install: global only
    token = install_delta(d)
    try:
        reg.counter("x.hits").add(3.0)
    finally:
        uninstall_delta(token)
    reg.counter("x.hits").add(5.0)  # after uninstall: global only
    assert reg.counters["x.hits"].total == 10.0
    assert d.snapshot() == {"x.hits": 3.0}


def test_delta_key_shapes_match_snapshot():
    """Timers and histograms land under the SAME key shapes the
    registry snapshot uses, so delta dicts diff against snapshots."""
    reg = MetricsRegistry()
    d = QueryMetricsDelta()
    token = install_delta(d)
    try:
        reg.timer("t.dispatch").add(0.5)
        reg.histogram("h.lat").add(0.25)
        reg.histogram("h.lat").add(0.75)
    finally:
        uninstall_delta(token)
    snap = d.snapshot()
    assert snap["t.dispatch.count"] == 1.0
    assert snap["t.dispatch.total_s"] == pytest.approx(0.5)
    assert snap["h.lat.count"] == 2.0
    assert snap["h.lat.total"] == pytest.approx(1.0)
    for key in snap:
        assert key in reg.snapshot() or key.endswith(".total"), key


def test_delta_thread_isolation_and_global_conservation():
    """N threads, each under its OWN collector, bumping the SAME
    counter: every thread's delta sees exactly its own adds and the
    global total is the exact union — the no-bleed contract."""
    reg = MetricsRegistry()
    n_threads, per_thread = 8, 200
    deltas = [QueryMetricsDelta() for _ in range(n_threads)]
    errors = []

    def worker(i):
        token = install_delta(deltas[i])
        try:
            for _ in range(per_thread):
                reg.counter("shared.counter").add()
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            uninstall_delta(token)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert reg.counters["shared.counter"].total == n_threads * per_thread
    for d in deltas:
        assert d.snapshot() == {"shared.counter": float(per_thread)}


def test_queryinfo_attribute_metrics_derivations():
    info = QueryInfo(query_id="q", sql="", state="FINISHED",
                     created_at=0.0)
    info.attribute_metrics({
        "join.strategy.pallas": 2.0,
        "join.strategy.grouped": 1.0,
        "join.strategy.dense": 0.0,  # zero: not executed, not listed
        "join.filter_selectivity.count": 2.0,
        "join.filter_selectivity.total": 0.5,
        "query.oom_degraded": 3.0,
        "exec.traces": 0.0,  # zero-valued deltas are dropped
    })
    assert info.join_strategy == "grouped,pallas"
    assert info.filter_selectivity == pytest.approx(0.25)
    assert info.oom_rung == 3
    assert "exec.traces" not in info.metrics
    assert "join.strategy.dense" not in info.metrics


def test_queryinfo_no_filter_observations_reports_minus_one():
    info = QueryInfo(query_id="q", sql="", state="FINISHED",
                     created_at=0.0)
    info.attribute_metrics({"join.strategy.expand": 1.0})
    assert info.filter_selectivity == -1.0
    assert info.oom_rung == 0


# ---------------------------------------------------------------------------
# end-to-end attribution through the engine
# ---------------------------------------------------------------------------


def test_query_info_carries_join_strategy_deltas(conn):
    s = Session({"tpch": conn},
                properties={"result_cache_enabled": False})
    _df, info = s.execute(_q3())
    assert info.metrics.get("join.strategy.pallas", 0) >= 1
    assert "pallas" in info.join_strategy
    j = json.loads(info.to_json())
    assert j["joinStrategy"] == info.join_strategy
    assert j["metrics"]["join.strategy.pallas"] >= 1
    assert "oomRung" in j and "filterSelectivity" in j


def test_cache_hit_query_has_empty_metrics(conn):
    """A result-cache hit never reaches run_plan — no execution, no
    attributed deltas (the node-stats 'not executed' analog)."""
    s = Session({"tpch": conn})
    q = "select count(*) c from nation"
    s.execute(q)  # populate
    _df, info = s.execute(q)
    assert info.cache_hit
    assert info.metrics == {}


def test_concurrent_queries_report_disjoint_strategies(conn):
    """The acceptance scenario: two queries run CONCURRENTLY on the one
    process-global registry — a fused-probe Q3 and a forced-grouped
    join — and each QueryInfo carries exactly its own
    ``join.strategy.*`` moves."""
    grouped_q = ("select count(*) c from lineitem "
                 "join orders on l_orderkey = o_orderkey")
    props_a = {"result_cache_enabled": False}
    props_b = {"result_cache_enabled": False,
               "join_build_budget_bytes": 1}
    # warm both signatures so the concurrent phase measures execution,
    # not a race between first compiles
    Session({"tpch": conn}, properties=props_a).sql(_q3())
    Session({"tpch": conn}, properties=props_b).sql(grouped_q)

    results: dict = {}
    errors: list = []
    barrier = threading.Barrier(2)

    def run(name, props, sql):
        try:
            s = Session({"tpch": conn}, properties=props)
            barrier.wait(timeout=60)
            _df, info = s.execute(sql)
            results[name] = info
        except Exception as e:  # noqa: BLE001
            errors.append(f"{name}: {type(e).__name__}: {e}")

    threads = [
        threading.Thread(target=run, args=("pallas", props_a, _q3())),
        threading.Thread(target=run,
                         args=("grouped", props_b, grouped_q)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
        assert not t.is_alive(), "concurrent query hung"
    assert not errors, errors
    pal, grp = results["pallas"].metrics, results["grouped"].metrics
    assert pal.get("join.strategy.pallas", 0) >= 1
    assert pal.get("join.strategy.grouped", 0) == 0
    assert grp.get("join.strategy.grouped", 0) >= 1
    assert grp.get("join.strategy.pallas", 0) == 0
    assert "grouped" not in results["pallas"].join_strategy
    # the grouped tier's per-bucket probes record their own strategy
    # (unique) beside the forced grouped decision — but never pallas
    assert "grouped" in results["grouped"].join_strategy
    assert "pallas" not in results["grouped"].join_strategy


def test_query_history_carries_attribution_columns(conn):
    s = Session({"tpch": conn},
                properties={"result_cache_enabled": False})
    s.execute(_q3())
    df = s.sql("select query_id, oom_rung, join_strategy, "
               "filter_selectivity from query_history")
    rows = df[df["join_strategy"].str.contains("pallas")]
    assert len(rows) >= 1
    assert (rows["oom_rung"] >= 0).all()


# ---------------------------------------------------------------------------
# OpenMetrics exposition
# ---------------------------------------------------------------------------

_SAMPLE = re.compile(
    r"^[a-zA-Z_][a-zA-Z0-9_]*(\{quantile=\"0\.\d+\"\})? -?\d+(\.\d+)?"
    r"(e-?\d+)?$"
)


def _parse_exposition(text: str) -> dict:
    """Minimal OpenMetrics parser: every line must be a comment
    (# TYPE / # HELP / # EOF) or a valid sample; returns
    {family: value}."""
    samples = {}
    lines = text.splitlines()
    assert lines[-1] == "# EOF"
    for line in lines[:-1]:
        if line.startswith("# TYPE "):
            parts = line.split()
            assert len(parts) == 4 and parts[3] in (
                "counter", "gauge", "summary", "histogram"), line
            continue
        if line.startswith("# HELP "):
            assert len(line.split()) >= 4, line  # family + some text
            continue
        assert _SAMPLE.match(line), f"unparseable sample line: {line!r}"
        name, _, value = line.rpartition(" ")
        samples[name] = float(value)
    return samples


def test_openmetrics_exposition_parses_and_has_known_counters(conn):
    s = Session({"tpch": conn})
    s.sql("select count(*) c from nation")
    text = s.export_metrics()
    samples = _parse_exposition(text)
    assert samples["presto_tpu_query_started_total"] >= 1
    assert samples["presto_tpu_query_completed_total"] >= 1
    # histogram families expose quantiles + count/sum
    assert 'presto_tpu_query_execution_s{quantile="0.5"}' in samples
    assert samples["presto_tpu_query_execution_s_count"] >= 1


def test_openmetrics_live_state_gauges(conn):
    """Session.export_metrics carries the live-state gauges the counter
    registry can't: pool occupancy, exec-cache entries, and the
    flight-recorder ring depth — each with TYPE gauge and a HELP line
    (to_openmetrics alone, with no gauges passed, emits none)."""
    s = Session({"tpch": conn})
    s.sql("select count(*) c from nation")
    text = s.export_metrics()
    samples = _parse_exposition(text)
    assert samples["presto_tpu_memory_pool_capacity_bytes"] > 0
    assert samples["presto_tpu_memory_pool_reserved_bytes"] >= 0
    assert samples["presto_tpu_exec_cache_entries"] >= 1
    assert samples["presto_tpu_flight_recorder_depth"] >= 0
    assert "# TYPE presto_tpu_exec_cache_entries gauge" in text
    assert "# HELP presto_tpu_flight_recorder_depth" in text
    bare = to_openmetrics(REGISTRY)
    assert "presto_tpu_exec_cache_entries" not in bare


def test_export_metrics_writes_path(tmp_path, conn):
    s = Session({"tpch": conn})
    s.sql("select count(*) c from region")
    p = tmp_path / "metrics.prom"
    text = s.export_metrics(str(p))
    assert p.read_text() == text
    assert text.endswith("# EOF\n")


def test_exposition_names_are_prometheus_safe():
    text = to_openmetrics(REGISTRY)
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        name = line.split("{")[0].split(" ")[0]
        assert re.fullmatch(r"[a-zA-Z_][a-zA-Z0-9_]*", name), name


# ---------------------------------------------------------------------------
# the post_run attribution bucket (ISSUE-9 satellite): counters bumped
# AFTER run_plan returns land under explicit post_run.* keys
# ---------------------------------------------------------------------------


def test_post_run_counters_attributed(conn):
    s = Session({"tpch": conn})
    _df, info = s.execute(
        "select count(*) c from lineitem where l_quantity < 10")
    # query.completed fires after run_plan's delta scope closes — it
    # was the documented attribution gap; now it lands in post_run.*
    assert info.metrics.get("post_run.query.completed") == 1.0
    # the result-cache populate also happens post-run
    assert info.metrics.get("post_run.result_cache.populated") == 1.0
    # in-run counters keep their plain (un-prefixed) keys
    assert "query.completed" not in info.metrics
    assert any(not k.startswith("post_run.") for k in info.metrics)


def test_post_run_bucket_on_failed_query(conn):
    s = Session({"tpch": conn})
    try:
        # fails at EXECUTION (scalar subquery yields >1 row) — analysis
        # errors never reach the tracked-query lifecycle
        s.execute("select (select l_orderkey from lineitem) x")
    except Exception:
        pass
    info = s.query_history[-1]
    assert info.state == "FAILED"
    assert info.metrics.get("post_run.query.failed") == 1.0
