"""Stats-driven narrow physical column storage (ISSUE-5).

The engine's native scan representation is now the narrowest signed-int
storage each column's declared bounds permit. These tests pin the
soundness contract: results are BIT-IDENTICAL with narrowing on vs off
— at the exact declared bound min/max, through scan -> filter -> join
-> aggregation -> sort -> host decode, per narrowable TypeKind
(BIGINT, INTEGER, DATE, TIMESTAMP, DECIMAL, VARCHAR codes) — plus the
plumbing invariants: range-guarded materialization, physical dtypes in
plan fingerprints, physical-width admission estimates, narrow wire
tensors on the distributed exchange, and the fused Q1 fragment route.
"""

from __future__ import annotations

import contextlib
import os

import numpy as np
import pandas.testing as pdt
import pytest

from presto_tpu.batch import Batch, Dictionary
from presto_tpu.spi import ColumnStats, Split, batch_capacity, narrowed_schema
from presto_tpu.types import (
    BIGINT,
    DATE,
    DOUBLE,
    INTEGER,
    TIMESTAMP,
    DataType,
    TypeKind,
    decimal,
    narrow_physical,
    varchar,
)


@contextlib.contextmanager
def narrow_env(value):
    """Pin PRESTO_TPU_NARROW for a block, restoring the prior state
    (sessions mirror the narrow_storage property into the env, so tests
    must not leak the switch)."""
    prior = os.environ.pop("PRESTO_TPU_NARROW", None)
    if value is not None:
        os.environ["PRESTO_TPU_NARROW"] = value
    try:
        yield
    finally:
        if value is not None or "PRESTO_TPU_NARROW" in os.environ:
            os.environ.pop("PRESTO_TPU_NARROW", None)
        if prior is not None:
            os.environ["PRESTO_TPU_NARROW"] = prior


# ---------------------------------------------------------------------------
# unit: the narrow chooser and schema derivation
# ---------------------------------------------------------------------------


def test_narrow_physical_chooser():
    assert narrow_physical(BIGINT, 0, 100).np_dtype == np.dtype(np.int8)
    assert narrow_physical(BIGINT, -127, 127).np_dtype == np.dtype(np.int8)
    # the dtype extreme stays free (unary negation must stay exact)
    assert narrow_physical(BIGINT, -128, 0).np_dtype == np.dtype(np.int16)
    assert narrow_physical(BIGINT, 0, 32767).np_dtype == np.dtype(np.int16)
    assert narrow_physical(BIGINT, 0, 32768).np_dtype == np.dtype(np.int32)
    assert narrow_physical(BIGINT, 0, 2**31 - 1).np_dtype == np.dtype(np.int32)
    assert not narrow_physical(BIGINT, 0, 2**31).is_narrowed
    # INTEGER (canonical int32) narrows to int8/int16 but never "to" int32
    assert narrow_physical(INTEGER, 0, 1000).np_dtype == np.dtype(np.int16)
    assert not narrow_physical(INTEGER, 0, 100000).is_narrowed
    # narrowed != canonical, canonical() round-trips, str stays logical
    t = narrow_physical(DATE, 0, 10000)
    assert t != DATE and t.canonical() == DATE
    assert str(t) == "date" and t.physical_str() == "date:int16"
    assert not narrow_physical(DOUBLE, 0, 1).is_narrowed


def test_narrowed_schema_switch_and_dictionary():
    types = {"a": BIGINT, "v": varchar()}
    dicts = {"v": Dictionary([f"s{i:03d}" for i in range(300)])}
    stats = {"a": ColumnStats(10, 0, 9)}
    with narrow_env(None):
        out = narrowed_schema(types, stats.get, dicts)
        assert out["a"].np_dtype == np.dtype(np.int8)
        assert out["v"].np_dtype == np.dtype(np.int16)  # 300 codes
    with narrow_env("0"):
        out = narrowed_schema(types, stats.get, dicts)
        assert not out["a"].is_narrowed and not out["v"].is_narrowed


def test_from_numpy_range_guard():
    t = narrow_physical(BIGINT, 0, 100)
    assert t.np_dtype == np.dtype(np.int8)
    with pytest.raises(ValueError, match="narrowed physical storage"):
        Batch.from_numpy({"a": np.array([0, 500], np.int64)}, {"a": t})


def test_scan_shares_live_validity():
    """NULL-free from_numpy columns share the live mask object — the
    identity the fused Q1 kernel's eligibility check keys on."""
    b = Batch.from_numpy({"a": np.arange(8)}, {"a": BIGINT}, capacity=16)
    assert b["a"].valid is b.live
    # an explicit NULL mask still gets its own validity array
    b2 = Batch.from_numpy(
        {"a": np.arange(8)}, {"a": BIGINT}, capacity=16,
        valids={"a": np.array([True] * 7 + [False])},
    )
    assert b2["a"].valid is not b2.live


# ---------------------------------------------------------------------------
# the bound-edge differential connector: every narrowable TypeKind with
# values AT the declared stats min/max
# ---------------------------------------------------------------------------

_N = 60


class EdgeConnector:
    """Two tiny tables whose declared stats are EXACT and whose data
    sits at the declared bound min/max for each narrowable kind."""

    name = "edge"

    def __init__(self):
        n = _N
        k = np.arange(n, dtype=np.int64)
        i16 = np.where(k % 2 == 0, -32767, 32767).astype(np.int64)
        i16[0], i16[1] = -32767, 32767
        i32 = np.where(k % 2 == 0, -(2**31 - 2), 2**31 - 2).astype(np.int64)
        dec = np.where(k % 3 == 0, -30000, 30000).astype(np.int64)  # +-300.00
        d = np.where(k % 2 == 0, -127, 127).astype(np.int64)
        ts = np.where(k % 2 == 0, -(10**6), 10**6).astype(np.int64)
        self._vdict = Dictionary([f"s{i:03d}" for i in range(200)])
        v = np.where(k % 2 == 0, 0, 199).astype(np.int64)
        nn = k.copy()
        nn_valid = (k % 5 != 0)
        self._tables = {
            "edge": {
                "arrays": {"k": k, "i16": i16, "i32": i32, "dec": dec,
                           "d": d, "ts": ts, "v": v, "nn": nn,
                           "nn$valid": nn_valid},
                "types": {"k": BIGINT, "i16": BIGINT, "i32": BIGINT,
                          "dec": decimal(12, 2), "d": DATE,
                          "ts": TIMESTAMP, "v": varchar(), "nn": BIGINT},
                "dicts": {"v": self._vdict},
                "stats": {
                    "k": ColumnStats(n, 0, n - 1),
                    "i16": ColumnStats(2, -32767, 32767),
                    "i32": ColumnStats(2, -(2**31 - 2), 2**31 - 2),
                    "dec": ColumnStats(2, -300.0, 300.0),
                    "d": ColumnStats(2, -127, 127),
                    "ts": ColumnStats(2, -(10**6), 10**6),
                    "nn": ColumnStats(n, 0, n - 1, null_fraction=0.2),
                },
            },
            "dim": {
                "arrays": {"dk": k, "tag": np.where(k % 2 == 0, 0, 1)
                           .astype(np.int64)},
                "types": {"dk": BIGINT, "tag": varchar()},
                "dicts": {"tag": Dictionary(["even", "odd"])},
                "stats": {"dk": ColumnStats(n, 0, n - 1)},
            },
        }

    def tables(self):
        return list(self._tables)

    def schema(self, table):
        return self._tables[table]["types"]

    def dictionaries(self, table):
        return self._tables[table]["dicts"]

    def row_count(self, table):
        return _N

    def stats(self, table, column):
        return self._tables[table]["stats"].get(column)

    def physical_schema(self, table, columns=None):
        t = self._tables[table]
        cols = list(columns) if columns is not None else list(t["types"])
        return narrowed_schema({c: t["types"][c] for c in cols},
                               lambda c: self.stats(table, c), t["dicts"])

    def splits(self, table, target_splits=0):
        return [Split(table, 0, 0, _N, _N)]

    def scan_numpy(self, split, columns=None):
        t = self._tables[split.table]
        keep = list(t["types"]) if columns is None else list(columns)
        out = {}
        for c in keep:
            out[c] = t["arrays"][c][split.lo:split.hi]
            if c + "$valid" in t["arrays"]:
                out[c + "$valid"] = t["arrays"][c + "$valid"][split.lo:split.hi]
        return out

    def scan(self, split, columns=None, capacity=None):
        from presto_tpu.spi import split_valids

        arrays, valids = split_valids(self.scan_numpy(split, columns))
        cap = capacity or batch_capacity(max(split.hi - split.lo, 1))
        types = self.physical_schema(split.table, list(arrays))
        t = self._tables[split.table]
        return Batch.from_numpy(
            arrays, types, capacity=cap, valids=valids,
            dictionaries={c: d for c, d in t["dicts"].items() if c in arrays},
        )


_EDGE_QUERY = """
select tag,
       sum(i16) s16, sum(i32) s32, sum(dec) sdec,
       min(d) dmin, max(d) dmax, min(ts) tsmin, max(ts) tsmax,
       min(v) vmin, max(v) vmax,
       count(nn) nncnt, sum(nn) nnsum, count(*) c
from edge join dim on k = dk
where i16 >= -32767 and d <= date '1970-05-07'
group by tag
order by tag
"""


def _run_edge(narrow: bool):
    from presto_tpu.runtime.session import Session

    with narrow_env("1" if narrow else "0"):
        s = Session({"edge": EdgeConnector()},
                    properties={"result_cache_enabled": False})
        df = s.sql(_EDGE_QUERY)
        phys = s.catalog.connector("edge").physical_schema("edge")
    return df, phys


def test_edge_bounds_differential():
    """Values at the exact declared min/max of every narrowed kind
    survive scan -> filter -> join -> agg -> sort -> decode identically
    to the canonical int64 path (the running sums exceed each narrow
    dtype's range, so any unwidened accumulation would wrap)."""
    narrow_df, phys = _run_edge(True)
    canon_df, canon_phys = _run_edge(False)
    assert phys["i16"].np_dtype == np.dtype(np.int16)
    assert phys["i32"].np_dtype == np.dtype(np.int32)
    assert phys["dec"].np_dtype == np.dtype(np.int16)
    assert phys["d"].np_dtype == np.dtype(np.int8)
    assert phys["ts"].np_dtype == np.dtype(np.int32)
    assert phys["v"].np_dtype == np.dtype(np.int16)
    assert phys["k"].np_dtype == np.dtype(np.int8)
    assert all(not t.is_narrowed for t in canon_phys.values())
    pdt.assert_frame_equal(narrow_df, canon_df)


def test_memory_connector_narrowing():
    """Written (CTAS-path) tables compute exact min/max stats at store
    time, so they narrow like generator tables — and round-trip
    identically to canonical storage."""
    import pandas as pd

    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.runtime.session import Session

    df = pd.DataFrame({
        "a": np.array([-32767, 32767, 5], np.int64),
        "b": np.array([1, 2, 3], np.int64),
        "s": ["x", "y", "x"],
    })

    def run(narrow):
        with narrow_env("1" if narrow else "0"):
            conn = MemoryConnector()
            conn.create_table("t", df)
            phys = conn.physical_schema("t")
            s = Session({"memory": conn},
                        properties={"result_cache_enabled": False})
            out = s.sql("select s, sum(a) sa, sum(b) sb from t "
                        "group by s order by s")
        return out, phys

    narrow_out, phys = run(True)
    canon_out, _ = run(False)
    assert phys["a"].np_dtype == np.dtype(np.int16)
    assert phys["b"].np_dtype == np.dtype(np.int8)
    pdt.assert_frame_equal(narrow_out, canon_out)


# ---------------------------------------------------------------------------
# the fused Q1 fragment route (eligibility on CPU; the kernel itself is
# TPU-gated and exactness-tested in tests/test_pallas_q1.py)
# ---------------------------------------------------------------------------


def test_q1_route_eligibility_and_kernel_supported():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.connectors.tpch.queries import QUERIES
    from presto_tpu.exec.q1_route import match_q1_fragment
    from presto_tpu.ops import pallas_q1
    from presto_tpu.plan import nodes as N
    from presto_tpu.runtime.session import Session

    conn = TpchConnector(sf=0.005)
    with narrow_env("1"):
        s = Session({"tpch": conn})
        plan = s.plan(QUERIES["q1"])

        agg = None

        def find(n):
            nonlocal agg
            if isinstance(n, N.Aggregate):
                agg = n
            for c in n.children:
                find(c)

        find(plan)
        assert agg is not None
        route = match_q1_fragment(agg, s.catalog)
        assert route is not None, "canonical TPC-H Q1 must match the route"
        assert set(route.rename.values()) == set(
            ("l_quantity", "l_extendedprice", "l_discount", "l_tax",
             "l_returnflag", "l_linestatus", "l_shipdate"))
        # the SQL-path scan batch is kernel-eligible at an aligned
        # capacity: narrow dtypes + live-shared validity
        split = conn.splits("lineitem")[0]
        b = conn.scan(split, list(route.rename), 1 << 16).rename(route.rename)
        assert pallas_q1.supported(b), (
            "SQL-path canonical scan batch must be narrow-kernel eligible")
        # and ineligible once narrowing is off (canonical int64 columns)
    with narrow_env("0"):
        b2 = conn.scan(split, list(route.rename), 1 << 16).rename(route.rename)
        assert not pallas_q1.supported(b2)


def test_q1_route_executes_and_matches_generic():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.connectors.tpch.queries import QUERIES
    from presto_tpu.runtime.metrics import REGISTRY
    from presto_tpu.runtime.session import Session

    conn = TpchConnector(sf=0.005)
    with narrow_env("1"):
        before = REGISTRY.snapshot().get("exec.q1_fused_route", 0)
        s = Session({"tpch": conn},
                    properties={"result_cache_enabled": False})
        routed = s.sql(QUERIES["q1"])
        assert REGISTRY.snapshot().get("exec.q1_fused_route", 0) > before
        # the stats recorder disables the route: same query, generic path
        s2 = Session({"tpch": conn},
                     properties={"result_cache_enabled": False,
                                 "collect_node_stats": True})
        generic = s2.sql(QUERIES["q1"])
    pdt.assert_frame_equal(routed, generic)


# ---------------------------------------------------------------------------
# plumbing: fingerprints, admission estimates, EXPLAIN, exchange bytes
# ---------------------------------------------------------------------------


def test_plan_fingerprint_includes_physical_dtype():
    from presto_tpu.cache.fingerprint import plan_fingerprint
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runtime.session import Session

    conn = TpchConnector(sf=0.005)
    q = "select sum(l_quantity) from lineitem"
    with narrow_env("1"):
        s = Session({"tpch": conn})
        fp_narrow = plan_fingerprint(s.plan(q), s.catalog, s.properties)
    with narrow_env("0"):
        fp_canon = plan_fingerprint(s.plan(q), s.catalog, s.properties)
    assert fp_narrow is not None and fp_canon is not None
    assert fp_narrow != fp_canon, (
        "physical dtypes must be part of the plan fingerprint")


def test_admission_estimates_use_physical_widths():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.plan.catalog import Catalog
    from presto_tpu.runtime.memory import estimate_node_bytes, node_row_bytes
    from presto_tpu.runtime.session import Session

    conn = TpchConnector(sf=0.005)
    catalog = Catalog({"tpch": conn})
    with narrow_env("1"):
        s = Session({"tpch": conn})
        plan = s.plan("select l_quantity, l_shipdate, l_suppkey from lineitem")
        scan = plan.child
        narrow_row = node_row_bytes(scan, catalog)
        narrow_est = estimate_node_bytes(scan, catalog)
    with narrow_env("0"):
        canon_row = node_row_bytes(scan, catalog)
        canon_est = estimate_node_bytes(scan, catalog)
    # qty 8->2, shipdate 4->2, suppkey 8->2 (sf .005): > 2x narrower
    assert narrow_row * 2 < canon_row
    assert narrow_est * 2 < canon_est


def test_explain_shows_physical_types():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runtime.session import Session

    with narrow_env("1"):
        s = Session({"tpch": TpchConnector(sf=0.005)})
        out = s.explain("select sum(l_quantity) q from lineitem "
                        "where l_shipdate <= date '1998-09-02'")
        assert "l_quantity:decimal(12,2):int16" in out
        assert "l_shipdate:date:int16" in out
        dist = s.explain_distributed(
            "select sum(l_quantity) q from lineitem")
        assert "l_quantity:int16" in dist


@pytest.mark.resets_global_state
def test_exchange_bytes_narrow_at_least_halves():
    """An int32-boundable repartition payload moves >= 2x fewer wire
    bytes than the int64 baseline (partitioned-window repartition of
    raw narrow scan columns on the 8-device virtual mesh), with
    identical rows.

    Marked ``resets_global_state``: the per-world byte measurement
    needs a from-zero ``exchange.bytes`` reading, so it REGISTRY.reset()s
    — declared so the conftest guard (and PT402) allow it."""
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.parallel.mesh import make_mesh
    from presto_tpu.runtime.metrics import REGISTRY
    from presto_tpu.runtime.session import Session

    q = ("select l_suppkey, l_quantity, l_shipdate, l_discount, l_tax, "
         "l_commitdate, l_receiptdate, l_linenumber, "
         "row_number() over (partition by l_suppkey order by l_quantity) rn "
         "from lineitem")
    conn = TpchConnector(sf=0.002)

    def run(narrow):
        with narrow_env("1" if narrow else "0"):
            REGISTRY.reset()
            s = Session({"tpch": conn}, mesh=make_mesh(8),
                        properties={"result_cache_enabled": False})
            df = s.sql(q)
            nbytes = REGISTRY.snapshot().get("exchange.bytes", 0)
        return df, nbytes

    narrow_df, narrow_bytes = run(True)
    canon_df, canon_bytes = run(False)
    assert narrow_bytes > 0 and canon_bytes > 0
    assert canon_bytes >= 2 * narrow_bytes, (
        f"exchange.bytes narrow={narrow_bytes} canonical={canon_bytes}")
    cols = list(narrow_df.columns)
    pdt.assert_frame_equal(
        narrow_df.sort_values(cols).reset_index(drop=True),
        canon_df.sort_values(cols).reset_index(drop=True),
    )


def test_global_agg_widens_narrow_sums():
    """An ungrouped sum over an int8-narrowed column whose total far
    exceeds int8 must widen before accumulating."""
    from presto_tpu.runtime.session import Session

    conn = EdgeConnector()
    with narrow_env("1"):
        s = Session({"edge": conn},
                    properties={"result_cache_enabled": False})
        out = s.sql("select sum(k) s, min(k) mn, max(k) mx from edge")
    assert int(out["s"][0]) == _N * (_N - 1) // 2
    assert int(out["mn"][0]) == 0 and int(out["mx"][0]) == _N - 1
