"""Kernel tests, differential against NumPy/pandas (reference parity:
operator-level unit tests w/ RowPagesBuilder+OperatorAssertion [SURVEY §4])."""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from presto_tpu.ops.compact import compact_indices
from presto_tpu.ops.groupby import (
    gather_padded,
    group_ids_direct,
    group_ids_sort,
    segment_agg,
)
from presto_tpu.ops.hashing import hash_columns, partition_ids
from presto_tpu.ops.join import (
    build_lookup,
    pack_key_columns,
    probe_exists,
    probe_expand,
    probe_unique,
)
from presto_tpu.ops.partition import partition_layout, scatter_to_buffer
from presto_tpu.ops.sort import sort_indices, top_n_indices


def _live(n, cap):
    m = np.zeros(cap, bool)
    m[:n] = True
    return jnp.asarray(m)


def test_compact_indices():
    mask = jnp.asarray(np.array([1, 0, 1, 1, 0, 0, 1, 0], bool))
    idx, n, ovf = compact_indices(mask, 6)
    assert int(n) == 4 and not bool(ovf)
    np.testing.assert_array_equal(np.asarray(idx)[:4], [0, 2, 3, 6])
    assert (np.asarray(idx)[4:] == 8).all()
    _, _, ovf2 = compact_indices(mask, 3)
    assert bool(ovf2)


def test_hash_determinism_and_order_sensitivity():
    a = jnp.asarray(np.arange(100, dtype=np.int64))
    b = jnp.asarray(np.arange(100, dtype=np.int64)[::-1].copy())
    h1 = hash_columns([a, b])
    h2 = hash_columns([a, b])
    np.testing.assert_array_equal(np.asarray(h1), np.asarray(h2))
    h3 = hash_columns([b, a])
    assert (np.asarray(h1) != np.asarray(h3)).any()
    p = partition_ids([a], 8)
    assert ((np.asarray(p) >= 0) & (np.asarray(p) < 8)).all()
    # distribution sanity: no partition empty for 100 sequential keys
    assert len(np.unique(np.asarray(p))) == 8


def test_group_ids_sort_vs_numpy(rng):
    cap, n, maxg = 64, 50, 32
    k1 = rng.integers(0, 5, cap).astype(np.int64)
    k2 = rng.integers(0, 3, cap).astype(np.int64)
    live = _live(n, cap)
    gids, rep, ng, ovf = group_ids_sort([jnp.asarray(k1), jnp.asarray(k2)], live, maxg)
    want_groups = set(zip(k1[:n].tolist(), k2[:n].tolist()))
    assert int(ng) == len(want_groups)
    assert not bool(ovf)
    # all rows of the same (k1,k2) share a gid; distinct pairs differ
    df = pd.DataFrame({"k1": k1[:n], "k2": k2[:n], "g": np.asarray(gids)[:n]})
    assert (df.groupby(["k1", "k2"])["g"].nunique() == 1).all()
    assert df["g"].nunique() == len(want_groups)
    # rep indices point at rows with matching keys
    rep = np.asarray(rep)
    for g in range(int(ng)):
        r = rep[g]
        assert r < cap
        assert np.asarray(gids)[r] == g


def test_group_ids_sort_overflow():
    cap = 32
    keys = jnp.asarray(np.arange(cap, dtype=np.int64))
    gids, rep, ng, ovf = group_ids_sort([keys], _live(cap, cap), 8)
    assert bool(ovf) and int(ng) == 32


def test_segment_agg_vs_pandas(rng):
    cap, n, maxg = 128, 100, 16
    k = rng.integers(0, 10, cap).astype(np.int64)
    v = rng.integers(-50, 50, cap).astype(np.int64)
    valid = rng.random(cap) > 0.2
    live = _live(n, cap)
    gids, rep, ng, _ = group_ids_sort([jnp.asarray(k)], live, maxg)
    contrib = jnp.asarray(valid) & live
    s = segment_agg(jnp.asarray(v), contrib, gids, maxg, "sum")
    c = segment_agg(jnp.asarray(v), contrib, gids, maxg, "count")
    mn = segment_agg(jnp.asarray(v), contrib, gids, maxg, "min")
    mx = segment_agg(jnp.asarray(v), contrib, gids, maxg, "max")
    df = pd.DataFrame({"k": k[:n], "v": v[:n], "ok": valid[:n]})
    df = df[df.ok]
    want = df.groupby("k")["v"].agg(["sum", "count", "min", "max"])
    gmap = {int(k[np.asarray(rep)[g]]): g for g in range(int(ng))}
    for key, row in want.iterrows():
        g = gmap[int(key)]
        assert int(np.asarray(s)[g]) == row["sum"]
        assert int(np.asarray(c)[g]) == row["count"]
        assert int(np.asarray(mn)[g]) == row["min"]
        assert int(np.asarray(mx)[g]) == row["max"]


def test_group_ids_direct():
    cap = 16
    flag = np.array([0, 1, 2, 0, 1, 2, 0, 0] + [0] * 8, dtype=np.int32)
    stat = np.array([0, 1, 0, 1, 0, 1, 0, 1] + [0] * 8, dtype=np.int32)
    live = _live(8, cap)
    gids, present = group_ids_direct(
        [jnp.asarray(flag), jnp.asarray(stat)], [0, 0], [2, 1], live, 6
    )
    # gid = flag*2 + stat
    np.testing.assert_array_equal(np.asarray(gids)[:8], [0, 3, 4, 1, 2, 5, 0, 1])
    assert (np.asarray(gids)[8:] == 6).all()
    assert np.asarray(present).all()


def test_join_unique_probe(rng):
    bcap, pcap = 32, 64
    bkeys = np.arange(1, 21, dtype=np.int64) * 3  # 3,6,...,60 unique
    bk = np.zeros(bcap, np.int64)
    bk[:20] = bkeys
    pkeys = rng.integers(1, 70, pcap).astype(np.int64)
    build = build_lookup(jnp.asarray(bk), _live(20, bcap), 32)
    assert not bool(build.overflow)
    res = probe_unique(build, jnp.asarray(pkeys), _live(pcap, pcap))
    for i in range(pcap):
        want = pkeys[i] in set(bkeys.tolist())
        assert bool(np.asarray(res.matched)[i]) == want
        if want:
            br = int(np.asarray(res.build_row)[i])
            assert bk[br] == pkeys[i]


def test_join_expand_vs_pandas(rng):
    bcap, pcap, ocap = 32, 16, 128
    bk = rng.integers(0, 6, bcap).astype(np.int64)  # duplicate keys
    pk = rng.integers(0, 8, pcap).astype(np.int64)
    bn, pn = 25, 12
    build = build_lookup(jnp.asarray(bk), _live(bn, bcap), 32)
    res = probe_expand(build, jnp.asarray(pk), _live(pn, pcap), ocap)
    assert not bool(res.overflow)
    got = []
    for j in range(ocap):
        if bool(np.asarray(res.live)[j]):
            got.append(
                (int(np.asarray(res.probe_row)[j]), int(np.asarray(res.build_row)[j]))
            )
    left = pd.DataFrame({"k": pk[:pn], "p": np.arange(pn)})
    right = pd.DataFrame({"k": bk[:bn], "b": np.arange(bn)})
    want = left.merge(right, on="k")
    want_pairs = set(zip(want["p"].tolist(), want["b"].tolist()))
    assert set(got) == want_pairs
    assert int(res.n_out) == len(want_pairs)


def test_join_expand_overflow():
    bcap, pcap = 16, 8
    bk = np.zeros(bcap, np.int64)  # all same key
    pk = np.zeros(pcap, np.int64)
    build = build_lookup(jnp.asarray(bk), _live(16, bcap), 16)
    res = probe_expand(build, jnp.asarray(pk), _live(8, pcap), 64)
    assert bool(res.overflow)  # 8*16=128 > 64
    assert int(res.n_out) == 128


def test_probe_exists():
    bk = jnp.asarray(np.array([2, 4, 6, 0], dtype=np.int64))
    build = build_lookup(bk, _live(3, 4), 4)
    pk = jnp.asarray(np.array([1, 2, 3, 4, 5, 6], dtype=np.int64))
    m = probe_exists(build, pk, _live(6, 6))
    np.testing.assert_array_equal(np.asarray(m), [False, True, False, True, False, True])


def test_sort_and_topn(rng):
    cap, n = 32, 20
    k1 = rng.integers(0, 5, cap).astype(np.int64)
    k2 = rng.integers(0, 100, cap).astype(np.int64)
    live = _live(n, cap)
    order = sort_indices([jnp.asarray(k1), jnp.asarray(k2)], [False, True], live)
    o = np.asarray(order)[:n]
    df = pd.DataFrame({"k1": k1[:n], "k2": k2[:n]}).sort_values(
        ["k1", "k2"], ascending=[True, False], kind="stable"
    )
    np.testing.assert_array_equal(k1[o], df["k1"].to_numpy())
    np.testing.assert_array_equal(k2[o], df["k2"].to_numpy())
    top = top_n_indices([jnp.asarray(k2)], [True], live, 5)
    want_top = np.sort(k2[:n])[::-1][:5]
    np.testing.assert_array_equal(np.sort(k2[np.asarray(top)])[::-1], want_top)


def test_sort_nulls_ordering():
    cap = 8
    k = jnp.asarray(np.array([3, 1, 2, 5, 4, 0, 0, 0], dtype=np.int64))
    valid = jnp.asarray(np.array([1, 1, 0, 1, 0, 0, 0, 0], bool))
    live = _live(5, cap)
    order = sort_indices([k], [False], live, nulls_first=[False], valids=[valid])
    o = np.asarray(order)[:5]
    np.testing.assert_array_equal(o, [1, 0, 3, 2, 4])  # 1,3,5 then nulls (2,4)
    order_nf = sort_indices([k], [False], live, nulls_first=[True], valids=[valid])
    onf = np.asarray(order_nf)[:5]
    np.testing.assert_array_equal(onf, [2, 4, 1, 0, 3])


def test_partition_roundtrip(rng):
    cap, n, P, Q = 64, 50, 4, 32
    keys = rng.integers(0, 1000, cap).astype(np.int64)
    live = _live(n, cap)
    pids = partition_ids([jnp.asarray(keys)], P)
    slot, counts, ovf = partition_layout(pids, live, P, Q)
    assert not bool(ovf)
    assert int(np.asarray(counts).sum()) == n
    buf = scatter_to_buffer(jnp.asarray(keys), slot, P, Q, fill=-1)
    got = np.asarray(buf)
    for p in range(P):
        want = sorted(keys[:n][np.asarray(pids)[:n] == p].tolist())
        have = sorted(x for x in got[p].tolist() if x != -1)
        assert want == have


def test_partition_overflow():
    cap, P, Q = 32, 4, 4
    keys = jnp.asarray(np.full(cap, 7, dtype=np.int64))  # all -> same pid
    pids = partition_ids([keys], P)
    slot, counts, ovf = partition_layout(pids, _live(32, cap), P, Q)
    assert bool(ovf)


def test_pack_key_columns():
    a = jnp.asarray(np.array([1, 2, 3], dtype=np.int64))
    b = jnp.asarray(np.array([0, 1, 0], dtype=np.int64))
    packed = pack_key_columns([a, b], [8, 1])
    np.testing.assert_array_equal(np.asarray(packed), [2, 5, 6])


# ---------------------------------------------------------------------------
# fused one-pass segment sums (the MXU one-hot matmul path)
# ---------------------------------------------------------------------------


def test_fused_small_sums_vs_numpy(rng):
    from presto_tpu.ops.groupby import fused_small_sums

    n, G = 70_001, 7
    gids = rng.integers(0, G + 1, n)  # includes the trash segment
    v1 = rng.integers(-5000, 5000, n)
    v2 = rng.integers(0, 1 << 24, n)
    v3 = rng.integers(-(1 << 31) + 1, 1 << 31, n)
    c1 = rng.random(n) < 0.9
    c2 = np.ones(n, bool)
    c3 = rng.random(n) < 0.5
    live = gids < G
    sums, counts, extras, of = fused_small_sums(
        [jnp.asarray(v1), jnp.asarray(v2), jnp.asarray(v3)],
        [13, 24, 31],
        [jnp.asarray(c1), jnp.asarray(c2), jnp.asarray(c3)],
        jnp.asarray(gids), G, extra_count_masks=(jnp.asarray(live),),
    )
    for i, (v, c) in enumerate([(v1, c1), (v2, c2), (v3, c3)]):
        want_s = np.array([v[(gids == g) & c].sum() for g in range(G)])
        want_n = np.array([((gids == g) & c).sum() for g in range(G)])
        np.testing.assert_array_equal(np.asarray(sums[i]), want_s)
        np.testing.assert_array_equal(np.asarray(counts[i]), want_n)
    np.testing.assert_array_equal(
        np.asarray(extras[0]), np.array([(gids == g).sum() for g in range(G)])
    )
    assert not bool(of)


def test_fused_small_sums_overflow_guard(rng):
    """A contributing |value| above the declared bound trips the flag;
    non-contributing rows never do."""
    from presto_tpu.ops.groupby import fused_small_sums

    n, G = 1024, 4
    gids = jnp.asarray(rng.integers(0, G, n))
    v = np.full(n, 100, np.int64)
    contrib = np.ones(n, bool)
    v[5] = 1 << 20  # exceeds 13 bits
    *_, of = fused_small_sums(
        [jnp.asarray(v)], [13], [jnp.asarray(contrib)], gids, G
    )
    assert bool(of)
    contrib[5] = False  # masked out -> no trip
    *_, of2 = fused_small_sums(
        [jnp.asarray(v)], [13], [jnp.asarray(contrib)], gids, G
    )
    assert not bool(of2)


def test_fused_small_sums_multichunk(rng, monkeypatch):
    import presto_tpu.ops.groupby as gb

    monkeypatch.setattr(gb, "_MM_CHUNK", 1 << 10)
    n, G = 5000, 3
    gids = rng.integers(0, G + 1, n)
    v = rng.integers(-(1 << 30), 1 << 30, n)
    c = rng.random(n) < 0.7
    sums, counts, _, _ = gb.fused_small_sums(
        [jnp.asarray(v)], [31], [jnp.asarray(c)], jnp.asarray(gids), G
    )
    np.testing.assert_array_equal(
        np.asarray(sums[0]),
        np.array([v[(gids == g) & c].sum() for g in range(G)]),
    )


def test_integer_sum_never_wraps_input_dtype(rng):
    """sum(int32 column) must accumulate in int64 (SQL types sum(int)
    as bigint): a group whose sum exceeds 2^31 must not wrap."""
    from presto_tpu.ops.groupby import fused_small_sums, segment_agg

    n = 300_000
    v32 = np.full(n, 9_999, np.int32)  # sum ~3e9 > 2^31
    gids = jnp.zeros(n, jnp.int32)
    contrib = jnp.ones(n, bool)
    want = np.int64(9_999) * n

    s = segment_agg(jnp.asarray(v32), contrib, gids, 2, "sum", value_bits=14)
    assert s.dtype == jnp.int64 and int(s[0]) == want
    # large-G scatter path
    s2 = segment_agg(jnp.asarray(v32), contrib, gids, 64, "sum")
    assert s2.dtype == jnp.int64 and int(s2[0]) == want
    (s3,), _, _, of = fused_small_sums(
        [jnp.asarray(v32)], [14], [contrib], gids, 2
    )
    assert s3.dtype == jnp.int64 and int(s3[0]) == want and not bool(of)
