"""Closed-loop overload control (ISSUE 19): load shedding, deadline
propagation, cooperative cancellation, retry budgets, and brown-out.

The contract under test, rung by rung:

- a shed submission fails FAST with the typed retryable
  ``ServerOverloaded`` (HTTP 429 + Retry-After monotone in queue
  depth) and leaves NO state behind — no submit record, no waiter,
  no vtime burn;
- shedding is fair: a light tenant with no backlog is never shed to
  protect an aggressor's queue;
- a cancelled query observes the flag at the next cooperative
  checkpoint, fails with the typed ``QueryCancelled``, and releases
  every reservation through the ordinary failure paths;
- the retry budget turns a correlated-failure retry storm into a
  fail-fast breaker trip, and a half-open probe re-arms it;
- a brown-out routes opt-in tenants to the approx tier (flagged
  honestly) or sheds them, and recovers after a breach-free cooldown.
"""

import threading
import time

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.errors import (
    ExceededTimeLimit,
    QueryCancelled,
    ServerOverloaded,
    TransientFailure,
    UserError,
)
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.overload import (
    CancelScope,
    CostEwma,
    OverloadController,
    RetryBudget,
    shed_retry_after,
)
from presto_tpu.runtime.session import Session
from presto_tpu.server.frontend import QueryServer
from presto_tpu.server.scheduler import FairScheduler, TenantSpec

CONN = TpchConnector(sf=0.005)

JOIN_SQL = (
    "select n_name, count(*) c, sum(s_acctbal) b "
    "from supplier join nation on s_nationkey = n_nationkey "
    "group by n_name order by n_name"
)

QUIET = {"health_monitor": False, "result_cache_enabled": False}


def _counter(name):
    return REGISTRY.snapshot().get(name, 0.0)


# ---------------------------------------------------------------------------
# primitives: CancelScope / shed_retry_after / CostEwma
# ---------------------------------------------------------------------------


def test_cancel_scope_is_idempotent_and_typed():
    scope = CancelScope("q1")
    scope.check("anywhere")  # no-op until flipped
    assert scope.cancel("user asked") is True
    assert scope.cancel("second caller") is False  # first reason wins
    assert scope.cancelled and scope.reason == "user asked"
    with pytest.raises(QueryCancelled) as ei:
        scope.check("morsel-loop")
    assert ei.value.error_code == "QUERY_CANCELLED"
    assert not ei.value.retryable  # a decision, not a failure
    assert "q1" in str(ei.value) and "user asked" in str(ei.value)


def test_shed_retry_after_monotone_and_capped():
    hints = [shed_retry_after(q) for q in range(0, 50, 5)]
    assert hints == sorted(hints)
    assert len(set(hints)) == len(hints)  # STRICTLY monotone pre-cap
    assert shed_retry_after(10**9) == 30.0  # capped


def test_cost_ewma_first_sample_seeds_estimate():
    ewma = CostEwma(alpha=0.5)
    assert ewma.samples == 0 and ewma.value == 0.0
    ewma.update(4.0)
    assert ewma.value == 4.0  # no cold-start blend toward zero
    ewma.update(0.0)
    assert ewma.value == 2.0


# ---------------------------------------------------------------------------
# retry budget + circuit breaker
# ---------------------------------------------------------------------------


def test_retry_budget_storm_opens_breaker_then_probe_rearms():
    b = RetryBudget(capacity=3, refill_per_s=0.0, probe_cooldown_s=0.05)
    assert all(b.try_spend() for _ in range(3))  # independent faults sip
    assert b.try_spend() is False  # drained -> breaker OPEN
    assert b.snapshot()["state"] == "open"
    assert b.try_spend() is False  # open: fail fast, no token math
    time.sleep(0.06)
    assert b.try_spend() is True  # half-open: exactly ONE probe
    assert b.try_spend() is False  # concurrent retry denied mid-probe
    b.record_success()
    snap = b.snapshot()
    assert snap["state"] == "closed" and snap["tokens"] == 3.0


def test_retry_budget_probe_failure_reopens_breaker():
    b = RetryBudget(capacity=1, refill_per_s=0.0, probe_cooldown_s=0.05)
    assert b.try_spend()
    assert not b.try_spend()  # open
    time.sleep(0.06)
    assert b.try_spend()  # the probe
    b.record_failure()  # storm not over: re-open, cooldown restarts
    assert b.snapshot()["state"] == "open"
    assert not b.try_spend()


def test_retry_budget_caps_session_retry_storm():
    """Integration: a permanent fault under a generous retry_count must
    drain the budget and fail fast with the ORIGINAL typed error —
    never 1+retry_count attempts per fragment forever."""
    from presto_tpu.runtime import faults

    sess = Session(
        {"tpch": CONN},
        properties={
            "retry_count": 50,
            "retry_backoff_s": 0.0,
            "retry_budget_tokens": 2.0,
            "retry_budget_refill_per_s": 0.0,
        },
    )
    inj = faults.FaultInjector()
    inj.inject("scan", error=TransientFailure, times=None, probability=1.0)
    opened = _counter("overload.breaker_open")
    with faults.injected(inj):
        with pytest.raises(TransientFailure):
            sess.sql("select n_name from nation order by n_name")
    assert _counter("overload.breaker_open") == opened + 1
    assert sess.pool().reserved_bytes == 0


# ---------------------------------------------------------------------------
# load shedding at the fair scheduler
# ---------------------------------------------------------------------------


def _queue_waiters(sched, tenant, n, timeout_s=30.0, expect_depth=None):
    """Block ``n`` threads in ``sched.acquire(tenant)``; returns the
    join/cleanup closure. ``expect_depth`` is the total queue depth to
    wait for (defaults to ``n`` — the fresh-scheduler case)."""
    started = []
    expect = n if expect_depth is None else expect_depth

    def waiter():
        token = sched.acquire(tenant, timeout_s=timeout_s)
        sched.release(token)

    threads = [threading.Thread(target=waiter, daemon=True)
               for _ in range(n)]
    for t in threads:
        t.start()
        started.append(t)
    deadline = time.monotonic() + 10.0
    while sched.queue_depth() < expect and time.monotonic() < deadline:
        time.sleep(0.005)
    assert sched.queue_depth() >= expect, "waiters never queued"

    def drain():
        for t in started:
            t.join(timeout=30.0)
            assert not t.is_alive(), "queued waiter hung"

    return drain


def test_shed_spares_light_tenant_with_no_backlog():
    """Fairness under overload: the GLOBAL ceiling sheds only tenants
    that already have queued work. A light WFQ tenant with an empty
    queue always gets one spot in line — the aggressor that built the
    backlog is shed first, every time."""
    sched = FairScheduler(total_slots=1, global_queue_limit=2)
    hold = sched.acquire("aggressor")
    try:
        drain = _queue_waiters(sched, "aggressor", 2)
        # global ceiling reached by the aggressor's own backlog:
        with pytest.raises(ServerOverloaded) as ei:
            sched.check_shed("aggressor")
        assert ei.value.retryable and ei.value.retry_after_s > 0
        # ... but the light tenant (zero queued) is NOT shed
        sched.check_shed("light")
        with pytest.raises(ServerOverloaded):
            sched.acquire("aggressor", timeout_s=1.0)
    finally:
        sched.release(hold)
        drain()
    assert sched.queue_depth() == 0


def test_shed_retry_after_grows_with_queue_depth():
    """The Retry-After hint is a drain estimate: deeper queue, longer
    hint, monotonically."""
    sched = FairScheduler(total_slots=1, global_queue_limit=2)
    hold = sched.acquire("agg")
    try:
        drain2 = _queue_waiters(sched, "agg", 2)
        with pytest.raises(ServerOverloaded) as e1:
            sched.check_shed("agg")
        # deepen the backlog with FRESH tenants (each has zero queued,
        # so the global ceiling lets them take their one spot in line)
        drain3 = _queue_waiters(sched, "o1", 1, expect_depth=3)
        drain4 = _queue_waiters(sched, "o2", 1, expect_depth=4)
        with pytest.raises(ServerOverloaded) as e2:
            sched.check_shed("agg")
        assert e2.value.retry_after_s > e1.value.retry_after_s
    finally:
        sched.release(hold)
        drain2()
        drain3()
        drain4()
    assert sched.queue_depth() == 0


def test_tenant_ceiling_sheds_before_global():
    sched = FairScheduler(total_slots=1, tenant_queue_limit=1)
    hold = sched.acquire("t")
    try:
        drain = _queue_waiters(sched, "t", 1)
        with pytest.raises(ServerOverloaded):
            sched.check_shed("t")
        sched.check_shed("fresh")  # other tenants unaffected
    finally:
        sched.release(hold)
        drain()


def test_shed_leaves_no_ghost_state():
    """A shed submission must evaporate: no submit record, no waiter,
    no vtime stamp — retrying it later competes as if it never
    happened."""
    srv = QueryServer({"tpch": CONN}, total_slots=1,
                      shed_tenant_queue_limit=0, properties=QUIET)
    try:
        shed0 = _counter("overload.shed")
        depth0 = srv.scheduler.queue_depth()
        records0 = set(srv._queries)
        # tenant ceiling of 0: the shed verdict is synchronous at
        # accept time, before any queue or record state exists
        with pytest.raises(ServerOverloaded):
            srv.submit(JOIN_SQL, tenant="t")
        assert set(srv._queries) == records0  # no submit-record ghost
        assert srv.scheduler.queue_depth() == depth0  # no waiter ghost
        assert _counter("overload.shed") == shed0 + 1  # counted
        snap = {r["tenant"]: r for r in srv.scheduler.snapshot()}
        assert snap["t"]["queued"] == 0
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# cooperative cancellation + deadline propagation
# ---------------------------------------------------------------------------


def test_cancel_queued_query_is_typed_and_releases_nothing():
    """DELETE of a QUEUED query: observed at the slot boundary, typed
    QUERY_CANCELLED on the poll page, pool untouched."""
    srv = QueryServer({"tpch": CONN}, total_slots=1, properties=QUIET)
    try:
        hold = srv.scheduler.acquire("default")  # pin the only slot
        try:
            qid = srv.submit(JOIN_SQL)
            out = srv.cancel(qid, reason="test cancel")
            assert out["cancelled"] is True
        finally:
            srv.scheduler.release(hold)
        assert srv._queries[qid]["done"].wait(120)
        page = srv.poll(qid)
        assert page["state"] == "FAILED"
        assert page["errorCode"] == "QUERY_CANCELLED"
        assert srv.session.pool().reserved_bytes == 0
        # second cancel of a terminal query is a polite no-op
        assert srv.cancel(qid)["cancelled"] is False
        with pytest.raises(UserError):
            srv.cancel("nope")
    finally:
        srv.shutdown()


def test_session_cancel_unknown_query_returns_false():
    sess = Session({"tpch": CONN})
    assert sess.cancel("no-such-query") is False


def test_execute_deadline_is_typed_and_pool_drains():
    srv = QueryServer({"tpch": CONN}, properties=QUIET)
    try:
        with pytest.raises(ExceededTimeLimit):
            srv.execute(JOIN_SQL, deadline_s=0.0)
        assert srv.session.pool().reserved_bytes == 0
    finally:
        srv.shutdown()


def test_deadline_tightens_but_never_loosens_query_max_run_time():
    """The effective deadline is the TIGHTER of the request deadline
    and query_max_run_time."""
    from presto_tpu.runtime.lifecycle import REQUEST_DEADLINE

    sess = Session({"tpch": CONN},
                   properties={"query_max_run_time": 3600.0})
    token = REQUEST_DEADLINE.set(time.monotonic())  # already expired
    try:
        with pytest.raises(ExceededTimeLimit):
            sess.sql(JOIN_SQL)
    finally:
        REQUEST_DEADLINE.reset(token)
    assert sess.pool().reserved_bytes == 0
    # and a generous request deadline does not loosen a tight limit
    sess2 = Session({"tpch": CONN},
                    properties={"query_max_run_time": 0.0001})
    token = REQUEST_DEADLINE.set(time.monotonic() + 3600.0)
    try:
        with pytest.raises(ExceededTimeLimit):
            sess2.sql(JOIN_SQL)
    finally:
        REQUEST_DEADLINE.reset(token)


# ---------------------------------------------------------------------------
# brown-out degradation
# ---------------------------------------------------------------------------


def test_overload_controller_engages_and_recovers():
    ctl = OverloadController(cooldown_s=0.05)
    approx = TenantSpec("a", brownout="approx")
    noop = TenantSpec("n")
    assert not ctl.engaged
    assert ctl.mode_for(approx) is None  # quiet server: no degradation
    ctl.on_breach({"kind": "p99_regression"})
    assert ctl.engaged and ctl.engagements == 1
    assert ctl.mode_for(approx) == "approx"
    assert ctl.mode_for(noop) is None  # degradation is opt-in
    time.sleep(0.06)
    assert not ctl.engaged  # breach-free cooldown elapsed
    assert ctl.mode_for(approx) is None
    assert ctl.snapshot()["engaged"] is False


def test_overload_controller_force_pins_past_cooldown():
    ctl = OverloadController(cooldown_s=0.0)
    ctl.force(True)
    time.sleep(0.01)
    assert ctl.engaged  # pinned: cooldown of 0 would have recovered
    ctl.force(False)
    assert not ctl.engaged


def test_brownout_routes_approx_and_sheds_optin_tenants():
    srv = QueryServer(
        {"tpch": CONN},
        tenants=[TenantSpec("dash", brownout="approx"),
                 TenantSpec("batch", brownout="shed"),
                 TenantSpec("paying")],
        properties=dict(QUIET, brownout_cooldown_s=3600.0),
    )
    try:
        # quiet server: everyone serves exact, nothing flagged
        qid = srv.submit("select count(*) c from nation", tenant="dash")
        assert srv._queries[qid]["done"].wait(120)
        assert "approximate" not in srv.poll(qid)

        srv.overload.on_breach({"kind": "queue_depth"})  # health breach
        routed0 = _counter("brownout.approx_routed")

        qid = srv.submit("select count(*) c from nation", tenant="dash")
        assert srv._queries[qid]["done"].wait(120)
        page = srv.poll(qid)
        assert page["state"] == "FINISHED"
        assert page.get("approximate") is True  # flagged honestly
        assert _counter("brownout.approx_routed") == routed0 + 1

        with pytest.raises(ServerOverloaded) as ei:
            srv.submit("select count(*) c from nation", tenant="batch")
        assert ei.value.retryable

        # no brown-out policy -> untouched even while engaged
        qid = srv.submit("select count(*) c from nation", tenant="paying")
        assert srv._queries[qid]["done"].wait(120)
        assert "approximate" not in srv.poll(qid)

        # operator release: recovery re-arms exact service for everyone
        srv.overload.force(True)
        srv.overload.force(False)
        qid = srv.submit("select count(*) c from nation", tenant="dash")
        assert srv._queries[qid]["done"].wait(120)
        assert "approximate" not in srv.poll(qid)
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# HTTP surface: 429 + Retry-After, X-Presto-Deadline, DELETE
# ---------------------------------------------------------------------------


def test_http_overload_surface():
    import json
    import urllib.error
    import urllib.request

    from presto_tpu.server.frontend import HttpFrontend

    srv = QueryServer({"tpch": CONN}, submit_limit=1, total_slots=1,
                      properties=QUIET)
    fe = HttpFrontend(srv, port=0).start_background()
    base = f"http://127.0.0.1:{fe.port}"

    def req(method, path, body=None, headers=None):
        r = urllib.request.Request(base + path, data=body,
                                   headers=headers or {}, method=method)
        return urllib.request.urlopen(r, timeout=30)

    try:
        # saturate the single pending slot -> 429 + integral Retry-After
        srv._queries["stuck"] = {"state": "QUEUED"}
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("POST", "/v1/statement", b"select 1 a")
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        body = json.loads(ei.value.read())
        assert body["errorCode"] == "SERVER_OVERLOADED"
        assert body["retryAfterS"] > 0
        del srv._queries["stuck"]

        with pytest.raises(urllib.error.HTTPError) as ei:
            req("POST", "/v1/statement", b"select 1 a",
                {"X-Presto-Deadline": "not-a-number"})
        assert ei.value.code == 400

        with pytest.raises(urllib.error.HTTPError) as ei:
            req("DELETE", "/v1/statement/nope")
        assert ei.value.code == 400

        # cancel over HTTP: pin the slot so the query stays QUEUED
        hold = srv.scheduler.acquire("default")
        try:
            out = json.loads(req("POST", "/v1/statement", JOIN_SQL.encode(),
                                 {"X-Presto-Deadline": "600"}).read())
            qid = out["id"]
            out = json.loads(req("DELETE", f"/v1/statement/{qid}").read())
            assert out["cancelled"] is True
        finally:
            srv.scheduler.release(hold)
        assert srv._queries[qid]["done"].wait(120)
        page = json.loads(req("GET", f"/v1/statement/{qid}").read())
        assert page["state"] == "FAILED"
        assert page["errorCode"] == "QUERY_CANCELLED"
    finally:
        fe.shutdown()
        srv.shutdown()
