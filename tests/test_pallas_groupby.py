"""Pallas fused groupby kernel vs the XLA einsum path (interpret mode
on the CPU mesh; the TPU compile is probed at runtime with a visible
fallback). Exactness is bit-for-bit: both paths are integer-exact."""

import numpy as np
import pytest

import jax.numpy as jnp

from presto_tpu.ops.groupby import fused_small_sums
from presto_tpu.ops.pallas_groupby import fused_lane_sums, probe_supported

CAP = 1 << 16  # one lane chunk: eligible capacity


def _data(rng, cap=CAP, neg=True):
    g = jnp.asarray(rng.integers(0, 7, cap).astype(np.int32))  # 6 + trash
    lo = -(2**30) if neg else 0
    v1 = jnp.asarray(rng.integers(lo, 2**30, cap).astype(np.int64))
    v2 = jnp.asarray(rng.integers(-5000, 5000, cap).astype(np.int64))
    live = jnp.asarray(rng.random(cap) < 0.9)
    c2 = jnp.asarray(rng.random(cap) < 0.8) & live
    return g, [v1, v2], [live, c2]


def test_matches_einsum_path(rng):
    gids, values, contribs = _data(rng)
    want = fused_small_sums(values, [31, 13], contribs, gids, 6,
                            extra_count_masks=(contribs[0],))
    zeroed = [jnp.where(c, v, 0).astype(jnp.int32)
              for v, c in zip(values, contribs)]
    sums, counts, oflow = fused_lane_sums(
        zeroed, [31, 13], list(contribs), gids, 6)
    for a, b in zip(sums, want[0]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(counts, want[1]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert bool(oflow) == bool(want[3])
    assert not bool(oflow)


def test_fused_small_sums_routes_through_pallas(rng, monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_PALLAS", "1")
    gids, values, contribs = _data(rng)
    got = fused_small_sums(values, [31, 13], contribs, gids, 6,
                           extra_count_masks=(contribs[0],))
    monkeypatch.setenv("PRESTO_TPU_PALLAS", "0")
    want = fused_small_sums(values, [31, 13], contribs, gids, 6,
                            extra_count_masks=(contribs[0],))
    for a, b in zip(got[0], want[0]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(got[1], want[1]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(got[2][0]), np.asarray(want[2][0]))
    assert bool(got[3]) == bool(want[3])


def test_overflow_detection(rng):
    gids, values, contribs = _data(rng)
    # declare 13 bits for a column holding 30-bit values -> must flag
    zeroed = [jnp.where(c, v, 0).astype(jnp.int32)
              for v, c in zip(values, contribs)]
    _, _, oflow = fused_lane_sums(zeroed, [13, 13], list(contribs), gids, 6)
    assert bool(oflow)


def test_multi_major_accumulation(rng, monkeypatch):
    # exercise block accumulation AND cross-major int64 recombination
    # without 8M+ interpret-mode rows: shrink the major span so
    # cap=2^19 / forced 2^16 blocks -> nblk=8, spm=2, nmajor=4
    import presto_tpu.ops.pallas_groupby as PG

    monkeypatch.setattr(PG, "_MAJOR_ROWS", 1 << 17)
    monkeypatch.setattr(PG, "_block_rows", lambda cap, *a: 1 << 16)
    cap = 1 << 19
    gids, values, contribs = _data(rng, cap)
    zeroed = [jnp.where(c, v, 0).astype(jnp.int32)
              for v, c in zip(values, contribs)]
    sums, counts, oflow = fused_lane_sums(
        zeroed, [31, 13], list(contribs), gids, 6)
    g = np.asarray(gids)
    sel = g < 6
    for i, v in enumerate(zeroed):  # zeroed already folds the contrib mask
        vn = np.asarray(v).astype(np.int64)
        want = np.zeros(6, np.int64)
        np.add.at(want, g[sel], vn[sel])
        np.testing.assert_array_equal(np.asarray(sums[i]), want)


def test_probe_rejects_ineligible():
    assert not probe_supported([40], 1, 6, CAP)  # bits > 31
    assert not probe_supported([13], 1, 6, CAP + 3)  # misaligned capacity
    assert not probe_supported([13] * 20, 2, 32, CAP)  # slot blowup


def test_wide_value_overflow_trips_before_cast(rng, monkeypatch):
    # an int64 value beyond 31 bits would WRAP in the int32 cast; the
    # declared-bound guard must trip on the original dtype
    monkeypatch.setenv("PRESTO_TPU_PALLAS", "1")
    cap = CAP
    g = jnp.zeros(cap, jnp.int32)
    v = jnp.full(cap, (1 << 32) + 100, jnp.int64)
    live = jnp.ones(cap, jnp.bool_)
    sums, counts, extra, oflow = fused_small_sums(
        [v], [31], [live], g, 6)
    assert bool(oflow)

