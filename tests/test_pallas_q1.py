"""The fully-fused Pallas Q1 kernel (ops/pallas_q1.py) vs the generic
``q1_fused_step`` route, bit-for-bit, in interpret mode on CPU.

On CPU the workloads router never takes the Pallas path (backend
check), so ``q1_fused_step`` here is the independent generic
reference; ``pallas_q1.q1_step`` runs the kernel under interpret.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from presto_tpu.batch import Batch, Column
from presto_tpu.ops import pallas_q1
from presto_tpu.types import BIGINT, DATE, decimal, varchar
from presto_tpu.workloads import Q1_COLS, q1_fused_step

CAP = 1 << 16


def _narrow_batch(rng, cap=CAP, rows=None):
    rows = cap if rows is None else rows
    dec2 = decimal(12, 2)
    mk = {
        "l_shipdate": (np.int16, 9000, 11500, DATE),  # straddles cutoff
        "l_returnflag": (np.int8, 0, 3, varchar()),
        "l_linestatus": (np.int8, 0, 2, varchar()),
        "l_quantity": (np.int16, 100, 5001, dec2),
        "l_extendedprice": (np.int32, 90000, 10_500_000, dec2),
        "l_discount": (np.int8, 0, 11, dec2),
        "l_tax": (np.int8, 0, 9, dec2),
    }
    cols = {}
    for name, (dt, lo, hi, typ) in mk.items():
        cols[name] = Column(
            jnp.asarray(rng.integers(lo, hi, cap).astype(dt)), None, typ)
    live = np.zeros(cap, np.bool_)
    live[:rows] = True
    return Batch(cols, jnp.asarray(live))


def _canonical(b: Batch) -> Batch:
    cols = {n: Column(c.data.astype(jnp.int64), c.valid, c.dtype)
            for n, c in b.columns.items()}
    return Batch(cols, b.live)


@pytest.mark.parametrize("rows", [CAP, CAP - 1371])
def test_matches_generic_route(rng, rows):
    b = _narrow_batch(rng, rows=rows)
    want = jax.jit(q1_fused_step)(_canonical(b))
    got = pallas_q1.q1_step(b)
    for k in ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
              "count_order"):
        np.testing.assert_array_equal(
            np.asarray(got[k]), np.asarray(want[k]), err_msg=k)
    np.testing.assert_array_equal(
        np.asarray(got["present"]), np.asarray(want["present"]))
    assert bool(got["value_overflow"]) == bool(want["value_overflow"])
    assert not bool(got["value_overflow"])


def test_overflow_guard_fires_on_discount_range(rng):
    b = _narrow_batch(rng)
    disc = np.array(b["l_discount"].data)
    disc[11] = -56  # dp = ep*156 could wrap int32 silently
    ship = np.array(b["l_shipdate"].data)
    ship[11] = 9100  # under the cutoff: the row must contribute
    cols = dict(b.columns)
    cols["l_discount"] = Column(jnp.asarray(disc), None, decimal(12, 2))
    cols["l_shipdate"] = Column(jnp.asarray(ship), None, DATE)
    got = pallas_q1.q1_step(Batch(cols, b.live))
    assert bool(got["value_overflow"])


def test_overflow_guard_fires(rng):
    b = _narrow_batch(rng)
    data = np.array(b["l_extendedprice"].data)
    data[7] = 1 << 25  # beyond the 24-bit declared bound
    ship = np.array(b["l_shipdate"].data)
    ship[7] = 9100  # under the cutoff: the row must contribute
    cols = dict(b.columns)
    cols["l_extendedprice"] = Column(jnp.asarray(data), None, BIGINT)
    cols["l_shipdate"] = Column(jnp.asarray(ship), None, DATE)
    got = pallas_q1.q1_step(Batch(cols, b.live))
    assert bool(got["value_overflow"])


def test_overflow_guard_fires_on_group_domain(rng):
    """An out-of-domain returnflag/linestatus code must flag loudly:
    gid = rf*2 + ls is neither clipped nor range-checked, so without
    the guard the row would silently vanish from every group AND from
    count_order (the generic route clips into the domain instead)."""
    b = _narrow_batch(rng)
    rf = np.array(b["l_returnflag"].data)
    rf[3] = 5  # gid = 10 >= G: outside every group
    ship = np.array(b["l_shipdate"].data)
    ship[3] = 9100  # under the cutoff: the row must contribute
    cols = dict(b.columns)
    from presto_tpu.types import varchar

    cols["l_returnflag"] = Column(jnp.asarray(rf), None, varchar())
    cols["l_shipdate"] = Column(jnp.asarray(ship), None, DATE)
    got = pallas_q1.q1_step(Batch(cols, b.live))
    assert bool(got["value_overflow"])


def test_eligibility():
    rng = np.random.default_rng(0)
    b = _narrow_batch(rng)
    assert pallas_q1.supported(b)
    assert not pallas_q1.supported(_canonical(b))  # int64 columns
    assert pallas_q1._block_rows(CAP + 3) is None  # misaligned capacity
