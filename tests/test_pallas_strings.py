"""Pallas string kernels vs the jnp reference kernels (differential:
same inputs, every pattern shape) — interpreter mode on the CPU mesh,
compiled on real TPU [SURVEY §4 fuzz-ish tier; config 5]."""

import numpy as np
import pytest

from presto_tpu.ops.pallas_strings import like_mask_pallas, starts_with_pallas
from presto_tpu.ops.strings import like_mask, starts_with_mask


def _rows(rng, n, width, vocab):
    """Random zero-padded byte rows composed from vocabulary words."""
    out = np.zeros((n, width), dtype=np.uint8)
    for i in range(n):
        s = b" ".join(rng.choice(vocab) for _ in range(rng.integers(1, 5)))[:width]
        out[i, : len(s)] = np.frombuffer(s, dtype=np.uint8)
    return out


VOCAB = [w.encode() for w in
         ["sky", "blue", "skyblue", "almond", "antique", "sly", "s", "bluesky"]]

PATTERNS = [
    "%sky%",            # contains
    "sky%",             # prefix
    "%blue",            # suffix
    "%sky%blue%",       # ordered segments
    "almond%antique",   # anchored both ends
    "%skyblue%",
    "sly",              # exact (no wildcard)
    "%zzz%",            # never matches
]


@pytest.fixture(scope="module")
def data(rng):
    return _rows(np.random.default_rng(11), 513, 44, VOCAB)


@pytest.mark.parametrize("pattern", PATTERNS)
def test_like_pallas_matches_reference(data, pattern):
    ref = np.asarray(like_mask(data, pattern))
    got = np.asarray(like_mask_pallas(data, pattern))
    np.testing.assert_array_equal(got, ref, err_msg=pattern)
    # sanity: the interesting patterns hit at least one row
    if pattern not in ("%zzz%", "almond%antique", "sly"):
        assert ref.any()


def test_like_edge_semantics(data):
    """Over-length literals never match; LIKE '' matches only empty
    rows; all-wildcard patterns match everything."""
    w = data.shape[1]
    long_lit = "x" * (w + 3)
    for fn in (like_mask, like_mask_pallas):
        assert not np.asarray(fn(data, long_lit)).any()
        empties = np.asarray(fn(data, ""))
        lens = (data != 0).sum(axis=1)
        np.testing.assert_array_equal(empties, lens == 0)
        assert np.asarray(fn(data, "%%")).all()


def test_like_suffix_with_repeats():
    """End-anchored segment occurring mid-string too (the '%1' bug)."""
    rows = [b"ab1cd1", b"ab1cd2", b"1", b"x1y", b""]
    data = np.zeros((5, 8), np.uint8)
    for i, r in enumerate(rows):
        data[i, : len(r)] = np.frombuffer(r, np.uint8)
    want = [r.endswith(b"1") for r in rows]
    for fn in (like_mask, like_mask_pallas):
        np.testing.assert_array_equal(np.asarray(fn(data, "%1")), want)


def test_use_pallas_env_values(monkeypatch):
    from presto_tpu.ops.strings import use_pallas

    for v in ("0", "false", "False", "off", "no", ""):
        monkeypatch.setenv("PRESTO_TPU_PALLAS", v)
        assert not use_pallas(), v
    for v in ("1", "true", "on"):
        monkeypatch.setenv("PRESTO_TPU_PALLAS", v)
        assert use_pallas(), v


def test_starts_with_pallas_matches_reference(data):
    for prefix in ["sky", "al", "blue", "zz"]:
        ref = np.asarray(starts_with_mask(data, prefix))
        got = np.asarray(starts_with_pallas(data, prefix))
        np.testing.assert_array_equal(got, ref, err_msg=prefix)


def test_like_pallas_via_sql(env_pallas):
    """Force the Pallas route through the SQL engine and diff against
    the jnp route on a real TPC-H predicate (q9-shape p_name LIKE)."""
    session, tables = env_pallas
    q = "select count(*) as n from part where p_name like '%green%'"
    got = int(session.sql(q)["n"][0])
    want = int(tables["part"]["p_name"].str.contains("green").sum())
    assert got == want and got > 0


@pytest.fixture(scope="module")
def env_pallas(monkeypatch_module):
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runtime.session import Session

    monkeypatch_module.setenv("PRESTO_TPU_PALLAS", "1")
    conn = TpchConnector(sf=0.005, units_per_split=1 << 14)
    session = Session({"tpch": conn})
    tables = {"part": conn.table_pandas("part")}
    return session, tables


@pytest.fixture(scope="module")
def monkeypatch_module():
    from _pytest.monkeypatch import MonkeyPatch

    mp = MonkeyPatch()
    yield mp
    mp.undo()


def test_empty_prefix_matches_everything():
    """starts_with('') is vacuously true; the kernel wrapper used to
    crash on an empty needle (round-1 advisor finding)."""
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.ops.pallas_strings import starts_with_pallas

    data = jnp.asarray(np.zeros((8, 12), np.uint8))
    out = np.asarray(starts_with_pallas(data, ""))
    assert out.all()


def test_probe_failure_is_logged(monkeypatch, caplog):
    import logging

    import presto_tpu.ops.pallas_strings as ps

    monkeypatch.setattr(ps, "_PROBE_CACHE", {})
    monkeypatch.setattr(ps, "_interpret", lambda: False)

    def boom(data, pattern):
        raise RuntimeError("mosaic compile crashed")

    with caplog.at_level(logging.WARNING, logger="presto_tpu.ops.pallas_strings"):
        ok = ps._probe("like", "x%", 12, boom)
    assert not ok
    assert any("falling back" in r.message for r in caplog.records)
