"""Multi-device tests on the virtual 8-device CPU mesh (reference
parity: DistributedQueryRunner — everything real except machines
[SURVEY §4])."""

import jax
import numpy as np
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.parallel.mesh import make_mesh, row_sharding
from presto_tpu.workloads import (
    combine_q1_states,
    q1_batch,
    q1_distributed_step,
    q1_fused_step,
)


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=0.01, units_per_split=1 << 14)


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_q1_distributed_matches_single(conn):
    batch = q1_batch(conn, capacity=1 << 17)
    single = jax.jit(q1_fused_step)(batch)

    mesh = make_mesh(8)
    sharded = jax.device_put(batch, row_sharding(mesh))
    dist = q1_distributed_step(mesh)(sharded)

    for k in single:
        np.testing.assert_array_equal(np.asarray(single[k]), np.asarray(dist[k]))


def test_graft_entry_contract():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert int(out["count_order"].sum()) > 0


def test_graft_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
