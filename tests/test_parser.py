"""Parser tests: all 22 TPC-H queries must parse; structural spot
checks (reference parity: presto-parser's TestSqlParser [SURVEY §4])."""

import pytest

from presto_tpu.connectors.tpch.queries import QUERIES
from presto_tpu.sql import ast as A
from presto_tpu.sql.parser import ParseError, parse


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpch_query_parses(name):
    q = parse(QUERIES[name])
    assert isinstance(q, A.Query)
    assert q.select


def test_q1_structure():
    q = parse(QUERIES["q1"])
    assert len(q.select) == 10
    assert q.select[3].alias == "sum_base_price"
    assert isinstance(q.from_, A.Table) and q.from_.name == "lineitem"
    assert len(q.group_by) == 2 and len(q.order_by) == 2
    # date arithmetic: date '1998-12-01' - interval '90' day
    w = q.where
    assert isinstance(w, A.BinaryOp) and w.op == "<="
    assert isinstance(w.right, A.BinaryOp) and isinstance(w.right.right, A.IntervalLit)


def test_q3_joins_and_limit():
    q = parse(QUERIES["q3"])
    assert q.limit == 10
    assert isinstance(q.from_, A.Join)
    assert q.order_by[0].descending


def test_q4_exists():
    q = parse(QUERIES["q4"])
    found = []

    def walk(n):
        if isinstance(n, A.Exists):
            found.append(n)
        for f in getattr(n, "__dataclass_fields__", {}):
            v = getattr(n, f)
            if isinstance(v, A.Node):
                walk(v)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, A.Node):
                        walk(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, A.Node):
                                walk(y)

    walk(q.where)
    assert len(found) == 1


def test_q7_aliased_nation_and_derived_table():
    q = parse(QUERIES["q7"])
    assert isinstance(q.from_, A.SubqueryRelation)
    assert q.from_.alias == "shipping"


def test_q13_left_join_with_composite_on():
    q = parse(QUERIES["q13"])
    sub = q.from_.query
    j = sub.from_
    assert isinstance(j, A.Join) and j.kind == "left"
    assert isinstance(j.on, A.BinaryOp) and j.on.op == "and"


def test_q15_with_cte():
    q = parse(QUERIES["q15"])
    assert len(q.ctes) == 1 and q.ctes[0][0] == "revenue"


def test_q16_not_in_subquery_and_count_distinct():
    q = parse(QUERIES["q16"])
    agg = q.select[3].expr
    assert isinstance(agg, A.FunctionCall) and agg.distinct


def test_q18_in_subquery_with_having():
    q = parse(QUERIES["q18"])
    # where contains InSubquery whose query has HAVING
    def find(n):
        if isinstance(n, A.InSubquery):
            return n
        if isinstance(n, A.BinaryOp):
            return find(n.left) or find(n.right)
        return None

    ins = find(q.where)
    assert ins is not None and ins.query.having is not None


def test_q22_substring_and_scalar_subquery():
    q = parse(QUERIES["q22"])
    sub = q.from_.query
    assert isinstance(sub.select[0].expr, A.Substring)


def test_parse_errors():
    with pytest.raises(ParseError):
        parse("select from t")
    with pytest.raises(ParseError):
        parse("select a t where")
    with pytest.raises(ParseError):
        parse("select a from t limit x")


def test_operator_precedence():
    q = parse("select 1 from t where a = 1 or b = 2 and c = 3")
    w = q.where
    assert w.op == "or"
    assert w.right.op == "and"
    q2 = parse("select 1 + 2 * 3 from t")
    e = q2.select[0].expr
    assert e.op == "+" and e.right.op == "*"


def test_not_precedence():
    q = parse("select 1 from t where not a = 1 and b = 2")
    w = q.where
    assert w.op == "and"
    assert isinstance(w.left, A.UnaryOp)


def test_quoted_identifiers_and_comments():
    q = parse('select "Weird Col" from t -- trailing comment\n/* block */')
    assert q.select[0].expr.parts == ("Weird Col",)


def test_cyclic_func_deps_keep_a_grouping_key():
    """Cyclic declared functional dependencies must not demote every
    grouping key (round-1 advisor finding: one-shot FD demotion)."""
    import pandas as pd

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.plan import nodes as N
    from presto_tpu.runtime.session import Session

    conn = TpchConnector(sf=0.01)
    s = Session({"tpch": conn})
    # declare a cyclic dependency n_name <-> n_nationkey on nation
    real_fd = s.catalog.func_deps

    def fake_fd(table):
        if table == "nation":
            return {"n_name": ("n_nationkey",), "n_nationkey": ("n_name",)}
        return real_fd(table)

    s.catalog.func_deps = fake_fd
    plan = s.plan("select n_nationkey, n_name, count(*) c from nation "
                  "group by n_nationkey, n_name")
    node = plan
    while not isinstance(node, N.Aggregate):
        node = node.children[0]
    assert len(node.keys) >= 1  # at least one real grouping key survives
    df = s.sql("select n_nationkey, n_name, count(*) c from nation "
               "group by n_nationkey, n_name order by n_nationkey")
    want = conn.table_pandas("nation")
    assert len(df) == len(want)
    pd.testing.assert_series_equal(
        df["c"], pd.Series([1] * len(want), name="c"), check_dtype=False
    )
