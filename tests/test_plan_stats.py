"""Estimate-vs-actual plan telemetry (ISSUE-8): the plan-time estimate
snapshot, the StatsRecorder output_rows accumulation fix, EXPLAIN
ANALYZE's est->actual / MISEST rendering, and the fingerprint-keyed
``system.plan_stats`` history with catalog-version invalidation.
"""

import re

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.session import Session
from presto_tpu.runtime.stats import (
    MISEST_FACTOR,
    StatsRecorder,
    misestimate_ratio,
)

Q_AGG = (
    "select l_returnflag, count(*) c, sum(l_quantity) q "
    "from lineitem group by l_returnflag order by l_returnflag"
)


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=0.005)


@pytest.fixture()
def session(conn):
    return Session({"tpch": conn},
                   properties={"result_cache_enabled": False})


# ---------------------------------------------------------------------------
# StatsRecorder semantics (satellite: output_rows accumulation)
# ---------------------------------------------------------------------------


class _FakeNode:
    children = ()


def test_record_output_rows_accumulates_across_invocations():
    """Regression: output_rows was last-write-wins while wall_s and
    output_bytes accumulated — a node invoked per batch under-reported
    its total rows in EXPLAIN ANALYZE and the finalize rollup."""
    rec = StatsRecorder()
    n = _FakeNode()
    rec.record(n, 0.1, 10, output_bytes=100)
    rec.record(n, 0.1, 15, output_bytes=150)
    rec.record(n, 0.1)  # unmeasured invocation: must not reset rows
    st = rec.stats_for(n)
    assert st.output_rows == 25
    assert st.output_bytes == 250
    assert st.invocations == 3


def test_finalize_input_rows_rollup_uses_accumulated_rows():
    class _Parent:
        def __init__(self, *children):
            self.children = children

    child = _FakeNode()
    parent = _Parent(child)
    rec = StatsRecorder()
    rec.record(child, 0.1, 7)
    rec.record(child, 0.1, 8)
    rec.record(parent, 0.2, 3)
    rec.finalize(parent)
    assert rec.stats_for(parent).input_rows == 15


def test_misestimate_ratio_edges():
    assert misestimate_ratio(100, 100) == 1.0
    assert misestimate_ratio(10, 1000) == 100.0
    assert misestimate_ratio(1000, 10) == 100.0
    assert misestimate_ratio(500, 0) == 500.0  # predicted rows, saw none
    assert misestimate_ratio(0, 100) == 0.0  # no estimate: unmeasured
    assert misestimate_ratio(None, 100) == 0.0
    assert misestimate_ratio(100, -1) == 0.0  # no actual: unmeasured


# ---------------------------------------------------------------------------
# plan-time estimate snapshot
# ---------------------------------------------------------------------------


def test_attach_estimates_covers_every_node(session):
    plan = session.plan(Q_AGG)
    rec = StatsRecorder()
    rec.attach_plan(plan)
    rec.attach_estimates(plan, session.catalog)

    def count(n):
        return 1 + sum(count(c) for c in n.children)

    assert len(rec.estimates) == count(plan)
    scan = plan
    while scan.children:
        scan = scan.children[0]
    est = rec.estimate_for(scan)
    # unfiltered scan: estimate equals row_count, sound bound is exact
    assert est.est_rows == session.catalog.connector("tpch").row_count(
        "lineitem")
    assert est.upper_bound_rows == est.est_rows
    assert est.exact
    assert est.row_bytes > 0


def test_estimate_record_exactness_tracks_predicates(session):
    from presto_tpu.plan.bounds import estimate_record

    exact = estimate_record(session.plan(
        "select l_orderkey from lineitem").children[0], session.catalog)
    filtered = estimate_record(session.plan(
        "select l_orderkey from lineitem where l_quantity < 10"
    ).children[0], session.catalog)
    assert exact["exact"] and exact["upper_bound_rows"] is not None
    assert not filtered["exact"]


def test_join_estimate_snapshots_planned_strategy(session):
    from presto_tpu.plan import nodes as N
    from presto_tpu.connectors.tpch.queries import QUERIES

    plan = session.plan(QUERIES["q3"])
    rec = StatsRecorder()
    rec.attach_plan(plan)
    rec.attach_estimates(plan, session.catalog)
    strategies = [
        e.strategy for e in rec.estimates.values()
        if e.node_type in ("Join", "SemiJoin")
    ]
    assert strategies and all(s for s in strategies)
    assert any(s in ("pallas", "dense", "unique", "expand", "grouped")
               for s in strategies)
    # aggregates carry the adaptive aggregation strategy (ISSUE-9);
    # every other non-join node stays strategy-free
    agg = [e.strategy for e in rec.estimates.values()
           if e.node_type == "Aggregate"]
    assert agg and all(
        s in ("fused", "bypass", "partial", "single") for s in agg)
    assert all(
        not e.strategy for e in rec.estimates.values()
        if e.node_type not in ("Join", "SemiJoin", "Aggregate")
    )


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE rendering
# ---------------------------------------------------------------------------


def test_explain_analyze_renders_est_actual_and_misest(session):
    out = session.explain_analyze(Q_AGG)
    # every executed node renders `est E->A (Nx)`
    assert re.search(r"est [\d,]+->[\d,]+ \(\d+(\.\d+)?x", out), out
    # the aggregate's /8 guess vs 3 groups is a flagged misestimate
    assert "MISEST" in out
    # a good estimate is NOT flagged (the unfiltered scan is near-exact)
    scan_line = next(l for l in out.splitlines() if "TableScan" in l)
    assert "MISEST" not in scan_line


def test_explain_analyze_renders_join_strategy(session):
    from presto_tpu.connectors.tpch.queries import QUERIES

    out = session.explain_analyze(QUERIES["q3"])
    join_lines = [l for l in out.splitlines() if "Join" in l]
    assert any("strategy=" in l for l in join_lines), out


def test_node_stats_json_carries_estimates(session):
    _df, info = session.execute(Q_AGG)
    by_type = {st["node"]: st for st in info.node_stats}
    agg = by_type["Aggregate"]
    assert agg["est_rows"] > 0
    assert agg["misest"] >= MISEST_FACTOR  # the /8 guess vs 3 groups
    scan = by_type["TableScan"]
    assert scan["est_rows"] > 0 and scan["misest"] < MISEST_FACTOR


def test_fragment_render_carries_sound_bounds(session):
    out = session.explain_distributed(
        "select l_returnflag, count(*) c from lineitem "
        "group by l_returnflag")
    assert "est<=" in out and "rows" in out


# ---------------------------------------------------------------------------
# plan-stats history store + system.plan_stats
# ---------------------------------------------------------------------------


def test_plan_stats_records_fingerprint_keyed_history(session):
    assert len(session.plan_stats) == 0
    session.execute(Q_AGG)
    assert len(session.plan_stats) == 1
    entry = list(session.plan_stats.entries())[0]
    assert entry.runs == 1
    by_type = {r["node_type"]: r for r in entry.records}
    scan = by_type["TableScan"]
    assert scan["actual_rows"] > 0 and scan["est_rows"] > 0
    assert 0 <= scan["selectivity"] <= 1 or scan["selectivity"] == -1.0
    # a repeat of the SAME plan lands under the SAME fingerprint
    session.execute(Q_AGG)
    assert len(session.plan_stats) == 1
    assert list(session.plan_stats.entries())[0].runs == 2
    # a different plan gets its own fingerprint
    session.execute("select count(*) c from nation")
    assert len(session.plan_stats) == 2


def test_system_plan_stats_table(session):
    session.execute(Q_AGG)
    df = session.sql(
        "select fingerprint, node_type, est_rows, actual_rows, "
        "selectivity, strategy, misest, runs from plan_stats")
    assert len(df) > 0
    assert (df["runs"] >= 1).all()
    scans = df[df["node_type"] == "TableScan"]
    assert len(scans) >= 1
    assert (scans["actual_rows"] > 0).all()
    # fingerprints are full sha256 hex
    assert df["fingerprint"].str.len().eq(64).all()


def test_plan_stats_invalidated_by_ddl(session):
    session.sql("create table obs_t as select l_orderkey, l_quantity "
                "from lineitem where l_quantity < 5")
    session.execute("select count(*) c from obs_t")
    n = len(session.plan_stats)
    entry_tables = [
        t for e in session.plan_stats.entries() for t, _v in e.versions
    ]
    assert "obs_t" in entry_tables
    # INSERT bumps the catalog version -> the eager listener drops the
    # obs_t history; unrelated fingerprints survive
    session.sql("insert into obs_t select l_orderkey, l_quantity "
                "from lineitem where l_quantity > 49")
    assert len(session.plan_stats) == n - 1
    assert not any(
        t == "obs_t"
        for e in session.plan_stats.entries() for t, _v in e.versions
    )
    df = session.sql("select node_type from plan_stats")
    assert len(df) == sum(
        len(e.records) for e in session.plan_stats.entries())
    session.sql("drop table obs_t")


def test_plan_stats_skips_volatile_plans(session):
    before = len(session.plan_stats)
    session.execute("select count(*) c from runtime_metrics")
    assert len(session.plan_stats) == before


def test_plan_stats_lru_bound(session):
    session.set_property("plan_stats_limit", 2)
    session.execute("select count(*) c from nation")
    session.execute("select count(*) c from region")
    session.execute("select count(*) c from supplier")
    assert len(session.plan_stats) == 2
    # a lowered limit evicts IMMEDIATELY (the query_history_limit
    # take-effect rule), not at the next recorded query
    session.set_property("plan_stats_limit", 1)
    assert len(session.plan_stats) == 1


def test_selectivity_histogram_rides_ratio_buckets():
    """Satellite: join.filter_selectivity must resolve the ratio-shaped
    buckets from the per-metric bounds registry, not the latency
    defaults (and every call site agrees by construction)."""
    from presto_tpu.runtime.metrics import (
        DEFAULT_BOUNDS,
        HISTOGRAM_BOUNDS,
        REGISTRY,
        SELECTIVITY_BOUNDS,
    )

    h = REGISTRY.histogram("join.filter_selectivity")
    assert h.bounds == SELECTIVITY_BOUNDS
    assert HISTOGRAM_BOUNDS["join.filter_selectivity"] == SELECTIVITY_BOUNDS
    assert REGISTRY.histogram("some.latency_metric").bounds == tuple(
        DEFAULT_BOUNDS)
