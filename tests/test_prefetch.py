"""ScanSource one-slot prefetch (SURVEY §2.4 PP row): split k+1's
generate/transfer must start while the consumer still holds split k,
and exactly one split may be in flight (bounded host memory)."""

import threading
import time

import numpy as np
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.pipeline import ScanSource


class RecordingConnector:
    """Wraps a real connector, recording scan start/end events."""

    def __init__(self, inner):
        self.inner = inner
        self.events = []
        self.started = [threading.Event() for _ in range(16)]
        self._n = 0
        self._lock = threading.Lock()

    def splits(self, table):
        return self.inner.splits(table)

    def scan(self, split, columns, capacity=None):
        with self._lock:
            i = self._n
            self._n += 1
        self.events.append(("start", i))
        self.started[i].set()
        out = self.inner.scan(split, columns, capacity)
        self.events.append(("end", i))
        return out


@pytest.fixture()
def source():
    conn = TpchConnector(sf=0.002, units_per_split=1 << 10)
    rec = RecordingConnector(conn)
    splits = conn.splits("lineitem")
    assert len(splits) >= 3, "fixture needs multiple splits"
    return rec, ScanSource(rec, "lineitem", ["l_quantity"], splits=splits)


def test_prefetch_overlaps_consumer(source, monkeypatch):
    # force-enable: the default is off on a 1-core host (measured GIL
    # contention — pipeline.prefetch_enabled)
    monkeypatch.setenv("PRESTO_TPU_PREFETCH", "1")
    rec, src = source
    it = iter(src)
    b0 = next(it)
    # while the consumer still holds split 0, split 1 must already be
    # loading on the prefetch thread
    assert rec.started[1].wait(timeout=10), (
        "split 1 scan did not start while split 0 was being consumed"
    )
    rest = list(it)
    assert 1 + len(rest) == len(src.splits)


def test_prefetch_is_single_slot(source, monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_PREFETCH", "1")
    rec, src = source
    it = iter(src)
    _ = next(it)
    time.sleep(0.5)  # give an over-eager prefetcher time to misbehave
    # only split 1 may be in flight: split 2 must NOT have started while
    # split 1's result has not been consumed
    assert not rec.started[2].is_set(), (
        "more than one split was prefetched ahead"
    )
    list(it)


def test_prefetch_rows_match_serial(source, monkeypatch):
    rec, src = source
    # force the prefetch path explicitly: the auto-default is serial on
    # a 1-core host, which would compare serial against serial
    monkeypatch.setenv("PRESTO_TPU_PREFETCH", "1")
    rows = sum(int(np.asarray(b.live).sum()) for b in src)
    monkeypatch.setenv("PRESTO_TPU_PREFETCH", "0")
    rows_serial = sum(int(np.asarray(b.live).sum()) for b in src)
    assert rows == rows_serial > 0
