"""Milestone A (SURVEY §7.3): TPC-H Q1 end-to-end on one device.

scan(lineitem) -> fused filter -> grouped aggregation (direct-addressed
returnflag x linestatus) -> 6 groups, validated against an exact
scaled-integer NumPy oracle that replicates the engine's decimal
rounding semantics. Both grouping strategies (direct, sort-merge) must
agree.
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch import schema as S
from presto_tpu.exec.operators import (
    AggSpec,
    DirectStrategy,
    FilterProjectOperator,
    HashAggregationOperator,
    SortStrategy,
)
from presto_tpu.exec.pipeline import Pipeline, ScanSource
from presto_tpu.expr import Call, col, lit
from presto_tpu.types import BIGINT, BOOLEAN, DATE, decimal, varchar

SF = 0.01
CUTOFF = "1998-09-02"
COLS = [
    "l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
    "l_discount", "l_tax", "l_shipdate",
]

dec2 = decimal(12, 2)
dec4 = decimal(38, 4)


def q1_aggs():
    one = lit(1, dec2)
    disc_price = Call(
        dec4, "mul",
        (col("l_extendedprice", dec2), Call(dec2, "sub", (one, col("l_discount", dec2)))),
    )
    charge = Call(
        dec4, "mul",
        (disc_price, Call(dec2, "add", (one, col("l_tax", dec2)))),
    )
    return [
        AggSpec("sum", col("l_quantity", dec2), "sum_qty", decimal(38, 2)),
        AggSpec("sum", col("l_extendedprice", dec2), "sum_base_price", decimal(38, 2)),
        AggSpec("sum", disc_price, "sum_disc_price", dec4),
        AggSpec("sum", charge, "sum_charge", dec4),
        AggSpec("count_star", None, "count_order", BIGINT),
    ]


def q1_pipeline(conn, strategy):
    pred = Call(
        BOOLEAN, "le", (col("l_shipdate", DATE), lit(CUTOFF, DATE))
    )
    return Pipeline(
        ScanSource(conn, "lineitem", COLS),
        [
            FilterProjectOperator(pred, None),
            HashAggregationOperator(
                [("l_returnflag", col("l_returnflag", varchar())),
                 ("l_linestatus", col("l_linestatus", varchar()))],
                q1_aggs(),
                strategy,
            ),
        ],
    )


def q1_oracle(conn):
    """Exact scaled-int oracle replicating engine decimal semantics."""
    li = conn.table_numpy("lineitem", COLS)
    cutoff = (np.datetime64(CUTOFF) - np.datetime64("1970-01-01")).astype(int)
    m = li["l_shipdate"] <= cutoff
    qty = li["l_quantity"][m].astype(np.int64)  # scale 2
    ep = li["l_extendedprice"][m].astype(np.int64)  # scale 2
    disc = li["l_discount"][m].astype(np.int64)  # scale 2
    tax = li["l_tax"][m].astype(np.int64)
    disc_price = ep * (100 - disc)  # scale 4 exact
    charge = (disc_price * (100 + tax) + 50) // 100  # s6 -> s4 half-away (all >= 0)
    df = pd.DataFrame(
        {
            "flag": li["l_returnflag"][m],
            "stat": li["l_linestatus"][m],
            "qty": qty,
            "ep": ep,
            "dp": disc_price,
            "ch": charge,
        }
    )
    g = df.groupby(["flag", "stat"]).agg(
        sum_qty=("qty", "sum"),
        sum_base=("ep", "sum"),
        sum_dp=("dp", "sum"),
        sum_ch=("ch", "sum"),
        n=("qty", "size"),
    )
    return g


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=SF, units_per_split=4096)


@pytest.mark.parametrize(
    "strategy",
    [DirectStrategy((0, 0), (2, 1), 6), SortStrategy(16)],
    ids=["direct", "sort"],
)
def test_q1_end_to_end(conn, strategy):
    out = q1_pipeline(conn, strategy).run()
    assert len(out) == 1
    res = out[0].to_pandas(logical=False)  # physical values (scaled ints)
    oracle = q1_oracle(conn)

    dflag = S.DICTS["l_returnflag"]
    dstat = S.DICTS["l_linestatus"]
    assert len(res) == len(oracle)
    got = {
        (dflag.values[r.l_returnflag] if isinstance(r.l_returnflag, (int, np.integer)) else r.l_returnflag,
         dstat.values[r.l_linestatus] if isinstance(r.l_linestatus, (int, np.integer)) else r.l_linestatus): r
        for r in res.itertuples()
    }
    for (fcode, scode), row in oracle.iterrows():
        key = (dflag.values[fcode], dstat.values[scode])
        r = got[key]
        assert int(r.sum_qty) == row.sum_qty
        assert int(r.sum_base_price) == row.sum_base
        assert int(r.sum_disc_price) == row.sum_dp
        assert int(r.sum_charge) == row.sum_ch
        assert int(r.count_order) == row.n


def test_q1_strategies_agree(conn):
    a = q1_pipeline(conn, DirectStrategy((0, 0), (2, 1), 6)).run()[0].to_pandas(logical=False)
    b = q1_pipeline(conn, SortStrategy(16)).run()[0].to_pandas(logical=False)
    a = a.sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)
    b = b.sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b)
