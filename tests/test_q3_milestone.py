"""Milestone B (SURVEY §7.2 step 4): TPC-H Q3 end-to-end —
customer ⋈ orders ⋈ lineitem, high-cardinality grouped agg, TopN.

select l_orderkey, sum(l_extendedprice*(1-l_discount)) revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment='BUILDING' and c_custkey=o_custkey
  and l_orderkey=o_orderkey and o_orderdate < '1995-03-15'
  and l_shipdate > '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""

import numpy as np
import pandas as pd
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.joins import BuildOutput, JoinBuildOperator, LookupJoinOperator
from presto_tpu.exec.operators import (
    AggSpec,
    FilterProjectOperator,
    HashAggregationOperator,
    SortKey,
    SortStrategy,
    TopNOperator,
)
from presto_tpu.exec.pipeline import Pipeline, ScanSource
from presto_tpu.expr import Call, col, lit
from presto_tpu.types import BIGINT, BOOLEAN, DATE, INTEGER, decimal, varchar

SF = 0.01
DATE_CUT = "1995-03-15"
dec2 = decimal(12, 2)
dec4 = decimal(38, 4)


def revenue_expr():
    one = lit(1, dec2)
    return Call(
        dec4, "mul",
        (col("l_extendedprice", dec2),
         Call(dec2, "sub", (one, col("l_discount", dec2)))),
    )


def run_q3(conn):
    # stage 1: customer build (filtered to BUILDING)
    cust_build = JoinBuildOperator(col("c_custkey", BIGINT))
    Pipeline(
        ScanSource(conn, "customer", ["c_custkey", "c_mktsegment"]),
        [
            FilterProjectOperator(
                Call(BOOLEAN, "eq",
                     (col("c_mktsegment", varchar()), lit("BUILDING", varchar()))),
                None,
            ),
            cust_build,
        ],
    ).run()

    # stage 2: orders filtered + semi-joined to customers -> build side 2
    orders_build = JoinBuildOperator(col("o_orderkey", BIGINT))
    Pipeline(
        ScanSource(conn, "orders",
                   ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"]),
        [
            FilterProjectOperator(
                Call(BOOLEAN, "lt", (col("o_orderdate", DATE), lit(DATE_CUT, DATE))),
                None,
            ),
            LookupJoinOperator(cust_build, col("o_custkey", BIGINT), (), "inner"),
            orders_build,
        ],
    ).run()

    # stage 3: lineitem probe -> agg -> topN
    p = Pipeline(
        ScanSource(conn, "lineitem",
                   ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"]),
        [
            FilterProjectOperator(
                Call(BOOLEAN, "gt", (col("l_shipdate", DATE), lit(DATE_CUT, DATE))),
                None,
            ),
            LookupJoinOperator(
                orders_build, col("l_orderkey", BIGINT),
                [BuildOutput("o_orderdate", "o_orderdate"),
                 BuildOutput("o_shippriority", "o_shippriority")],
                "inner",
            ),
            HashAggregationOperator(
                [("l_orderkey", col("l_orderkey", BIGINT)),
                 ("o_orderdate", col("o_orderdate", DATE)),
                 ("o_shippriority", col("o_shippriority", INTEGER))],
                [AggSpec("sum", revenue_expr(), "revenue", dec4)],
                SortStrategy(8192),
            ),
            TopNOperator(
                [SortKey(col("revenue", dec4), descending=True),
                 SortKey(col("o_orderdate", DATE))],
                10,
            ),
        ],
    )
    out = p.run()
    return pd.concat([b.to_pandas(logical=False) for b in out])


def q3_oracle(conn):
    cust = conn.table_pandas("customer", ["c_custkey", "c_mktsegment"])
    orders = conn.table_pandas(
        "orders", ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"]
    )
    li = conn.table_numpy(
        "lineitem", ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"]
    )
    cut = (np.datetime64(DATE_CUT) - np.datetime64("1970-01-01")).astype(int)
    m = li["l_shipdate"] > cut
    lid = pd.DataFrame(
        {
            "l_orderkey": li["l_orderkey"][m],
            "rev": li["l_extendedprice"][m].astype(np.int64)
            * (100 - li["l_discount"][m].astype(np.int64)),  # scale 4 exact
        }
    )
    cust = cust[cust.c_mktsegment == "BUILDING"]
    orders = orders[orders.o_orderdate < np.datetime64(DATE_CUT)]
    j = orders.merge(cust, left_on="o_custkey", right_on="c_custkey")
    j = lid.merge(j, left_on="l_orderkey", right_on="o_orderkey")
    g = (
        j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["rev"]
        .sum()
        .reset_index()
    )
    g = g.sort_values(
        ["rev", "o_orderdate"], ascending=[False, True], kind="stable"
    ).head(10)
    return g


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=SF, units_per_split=1 << 14)


def test_q3_end_to_end(conn):
    got = run_q3(conn)
    want = q3_oracle(conn)
    assert len(got) == len(want) == 10
    # revenues must match exactly (scaled ints); order by revenue desc
    np.testing.assert_array_equal(
        got["revenue"].to_numpy().astype(np.int64),
        want["rev"].to_numpy(),
    )
    np.testing.assert_array_equal(
        got["l_orderkey"].to_numpy().astype(np.int64),
        want["l_orderkey"].to_numpy(),
    )
    # o_orderdate comes back as raw day ints with logical=False
    want_days = (
        want["o_orderdate"].to_numpy().astype("datetime64[D]")
        - np.datetime64("1970-01-01")
    ).astype(np.int64)
    np.testing.assert_array_equal(
        got["o_orderdate"].to_numpy().astype(np.int64), want_days
    )
