"""Serving layer (presto_tpu/server, ISSUE-14): fairness scheduler,
cross-query batched dispatch, tenant attribution, HTTP surface.

The contract under test:

- FairScheduler: weighted-fair ordering (a light tenant's next query
  overtakes a flooding tenant's backlog), hard per-tenant quotas
  (concurrency + bytes) with loud counters, bounded queue timeouts.
- Batched dispatch: N same-template different-literal queries fuse
  into ONE vmapped device dispatch with results BIT-IDENTICAL to
  serial execution per binding; unbatchable templates fall back to the
  PR 9 serialized slot with per-reason counters; the result cache
  stays keyed per binding.
- Tenant attribution: QueryInfo.tenant rides to system.query_history;
  system.tenants exposes the scheduler's live state.
- HTTP round trip: /v1/statement submit+poll, /v1/prepared, /metrics.
"""

import json
import threading
import time
import urllib.request

import pandas as pd
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.errors import ResourceExhausted
from presto_tpu.runtime.lifecycle import QueryManager
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session
from presto_tpu.server.batcher import TemplateBatchGate, run_batched
from presto_tpu.server.frontend import HttpFrontend, QueryServer
from presto_tpu.server.scheduler import FairScheduler, TenantSpec

CONN = TpchConnector(sf=0.005)

#: a batchable template (TopN over a filtered scan: the serving-layer
#: load shape) and an unbatchable one (join under the aggregation)
TOPN_FMT = ("select l_orderkey, l_linenumber, l_quantity from lineitem"
            " where l_extendedprice < {}"
            " order by l_orderkey, l_linenumber limit 25")
AGG_FMT = ("select sum(l_extendedprice + {}) s, count(*) c,"
           " max(l_quantity) m from lineitem where l_partkey < {}")
JOIN_FMT = ("select o_orderpriority, count(*) c from lineitem"
            " join orders on l_orderkey = o_orderkey"
            " where l_extendedprice < {} group by o_orderpriority"
            " order by o_orderpriority")


def make_session(**props):
    props.setdefault("result_cache_enabled", False)
    return Session({"tpch": CONN}, properties=props)


def counter(name: str) -> float:
    return REGISTRY.snapshot().get(name, 0.0)


# ---------------------------------------------------------------------------
# fairness scheduler
# ---------------------------------------------------------------------------


def test_weighted_fairness_light_tenant_overtakes():
    """With one contended slot, a heavy tenant's backlog must NOT
    starve a light (higher-weight) tenant: the light tenant's first
    query carries a smaller virtual finish time and wins the slot."""
    sched = FairScheduler([TenantSpec("heavy", weight=1.0),
                           TenantSpec("light", weight=4.0)],
                          total_slots=1)
    tok = sched.acquire("heavy")
    order = []
    done = threading.Event()

    def grab(name):
        sched.acquire(name, timeout_s=20)
        order.append(name)
        sched.release(name)
        if len(order) == 2:
            done.set()

    t_heavy = threading.Thread(target=grab, args=("heavy",))
    t_heavy.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not sched.snapshot()[0]["queued"]:
        time.sleep(0.005)
    t_light = threading.Thread(target=grab, args=("light",))
    t_light.start()
    # wait until BOTH are queued, then free the slot
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        snap = {r["tenant"]: r for r in sched.snapshot()}
        if snap["heavy"]["queued"] and snap["light"]["queued"]:
            break
        time.sleep(0.005)
    sched.release(tok)
    assert done.wait(20)
    t_heavy.join(10)
    t_light.join(10)
    assert order == ["light", "heavy"], order


def test_weighted_fairness_overtakes_a_burst_backlog():
    """Enqueue-time vtime stamping: a BURST of waiters from one tenant
    carries stamps v+1, v+2, ..., so a light tenant's single query
    overtakes the whole backlog, not just one shared stamp."""
    sched = FairScheduler([TenantSpec("heavy", weight=1.0),
                           TenantSpec("light", weight=4.0)],
                          total_slots=1)
    tok = sched.acquire("heavy")
    order = []

    def grab(name):
        sched.acquire(name, timeout_s=30)
        order.append(name)
        sched.release(name)

    heavies = [threading.Thread(target=grab, args=("heavy",))
               for _ in range(4)]
    for t in heavies:
        t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        snap = {r["tenant"]: r for r in sched.snapshot()}
        if snap["heavy"]["queued"] == 4:
            break
        time.sleep(0.005)
    t_light = threading.Thread(target=grab, args=("light",))
    t_light.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        snap = {r["tenant"]: r for r in sched.snapshot()}
        if snap["light"]["queued"] == 1:
            break
        time.sleep(0.005)
    sched.release(tok)
    t_light.join(15)
    for t in heavies:
        t.join(15)
    assert order[0] == "light", order


def test_concurrency_quota_blocks_and_counts():
    sched = FairScheduler([TenantSpec("t", max_concurrent=1)])
    blocked0 = counter("tenant.over_quota_blocked")
    tok = sched.acquire("t")
    with pytest.raises(ResourceExhausted):
        sched.acquire("t", timeout_s=0.05)
    assert counter("tenant.over_quota_blocked") == blocked0 + 1
    snap = sched.snapshot()[0]
    assert snap["over_quota_blocked"] == 1
    assert snap["queue_timeouts"] == 1
    sched.release(tok)
    sched.release(sched.acquire("t", timeout_s=5))


def test_byte_quota_reads_tenant_tagged_pool_reservations():
    from presto_tpu.runtime.memory import MemoryPool

    pool = MemoryPool(1 << 30, name="quota-test")
    sched = FairScheduler([TenantSpec("t", max_bytes=1000)], pool=pool)
    pool.reserve("q1", 4096, tenant="t")
    assert pool.tenant_reserved_bytes("t") == 4096
    with pytest.raises(ResourceExhausted):
        sched.acquire("t", timeout_s=0.05)
    # release clears the tagged bytes and kicks the scheduler
    pool.release("q1")
    assert pool.tenant_reserved_bytes("t") == 0
    sched.release(sched.acquire("t", timeout_s=5))


def test_unknown_tenant_auto_registers_with_default_spec():
    sched = FairScheduler(default_spec=TenantSpec("default", weight=2.0))
    sched.release(sched.acquire("walk-in"))
    snap = {r["tenant"]: r for r in sched.snapshot()}
    assert snap["walk-in"]["admitted"] == 1
    assert snap["walk-in"]["weight"] == 2.0


# ---------------------------------------------------------------------------
# batched dispatch: bit-identity + fallbacks
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fmt,bindings", [
    (TOPN_FMT, [(2000,), (50000,), (91000,)]),
    (AGG_FMT, [(10, 500), (99, 1500)]),
])
def test_run_batched_bit_identical_to_serial(fmt, bindings):
    """One vmapped dispatch over stacked bindings must return frames
    bit-identical to each binding's serial execution (check_exact)."""
    s = make_session()
    handle = s.prepare(fmt.replace("{}", "?"))
    bounds = [handle.bind(list(b)) for b in bindings]
    dfs = run_batched(s.catalog, handle.plan, bounds)
    off = make_session(plan_templates=False)
    for b, df in zip(bindings, dfs):
        want = off.sql(fmt.format(*b))
        pd.testing.assert_frame_equal(df, want, check_exact=True)


def test_batched_gate_fuses_concurrent_bindings(monkeypatch):
    """Concurrent same-template different-literal queries meet at the
    batch gate: the first leader is held until the rest queue, then
    the next leader drains them into ONE fused dispatch. Results match
    serial execution exactly and the served queries are flagged."""
    s = make_session(batched_dispatch=True)
    s.sql(TOPN_FMT.format(1000))  # warm the template
    gate = s.query_manager.batch_gate
    release = threading.Event()
    orig = QueryManager.run_plan
    first = threading.Event()

    def gated(self, executor, plan, info, recorder):
        if not first.is_set():
            first.set()
            release.wait(30)
        return orig(self, executor, plan, info, recorder)

    monkeypatch.setattr(QueryManager, "run_plan", gated)
    lits = (2000, 20000, 50000, 91000)
    results = {}

    def worker(v):
        results[v] = s.sql(TOPN_FMT.format(v))

    d0 = counter("batch.dispatched")
    threads = [threading.Thread(target=worker, args=(v,)) for v in lits]
    threads[0].start()
    assert first.wait(30)
    for t in threads[1:]:
        t.start()
    # wait for the followers to queue at the gate, then release the
    # first leader; the next leader drains all three into one batch
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        depth = sum(gate.queue_depth(fp) for fp in list(gate._templates))
        if depth >= 3:
            break
        time.sleep(0.01)
    release.set()
    for t in threads:
        t.join(60)
    assert counter("batch.dispatched") >= d0 + 1, "no batch fused"
    off = make_session(plan_templates=False)
    for v in lits:
        pd.testing.assert_frame_equal(results[v], off.sql(TOPN_FMT.format(v)),
                                      check_exact=True)
    flags = [i.batched for i in s.query_history[-len(lits):]]
    assert sum(flags) >= 2, flags  # leader + served members


def test_unbatchable_template_falls_back_with_reason(monkeypatch):
    """A join-bearing template never batches: concurrent bindings ride
    the serialized template slot, the per-reason fallback counter
    fires, and results stay correct."""
    s = make_session(batched_dispatch=True)
    s.sql(JOIN_FMT.format(1000))  # warm
    orig = QueryManager.run_plan
    release = threading.Event()
    first = threading.Event()

    def gated(self, executor, plan, info, recorder):
        if not first.is_set():
            first.set()
            release.wait(30)
        return orig(self, executor, plan, info, recorder)

    monkeypatch.setattr(QueryManager, "run_plan", gated)
    f0 = counter("batch.fallback")
    d0 = counter("batch.dispatched")
    lits = (2000, 50000, 91000)
    results = {}

    def worker(v):
        results[v] = s.sql(JOIN_FMT.format(v))

    threads = [threading.Thread(target=worker, args=(v,)) for v in lits]
    threads[0].start()
    assert first.wait(30)
    for t in threads[1:]:
        t.start()
    gate = s.query_manager.batch_gate
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if sum(gate.queue_depth(fp) for fp in list(gate._templates)) >= 2:
            break
        time.sleep(0.01)
    release.set()
    for t in threads:
        t.join(60)
    assert counter("batch.dispatched") == d0, "join template batched!"
    assert counter("batch.fallback") > f0
    reasons = {k for k in REGISTRY.snapshot()
               if k.startswith("batch.fallback.")}
    assert reasons, "no per-reason fallback counter"
    off = make_session(plan_templates=False)
    for v in lits:
        pd.testing.assert_frame_equal(results[v], off.sql(JOIN_FMT.format(v)))


def test_batched_results_populate_result_cache_per_binding(monkeypatch):
    """A served member's frame lands in the result cache under ITS OWN
    binding fingerprint — batch sharing never blurs result identity."""
    s = Session({"tpch": CONN}, properties={"batched_dispatch": True})
    s.sql(TOPN_FMT.format(1000))
    orig = QueryManager.run_plan
    release = threading.Event()
    first = threading.Event()

    def gated(self, executor, plan, info, recorder):
        if not first.is_set():
            first.set()
            release.wait(30)
        return orig(self, executor, plan, info, recorder)

    monkeypatch.setattr(QueryManager, "run_plan", gated)
    lits = (7000, 44000)
    results = {}
    threads = [threading.Thread(
        target=lambda v=v: results.update({v: s.sql(TOPN_FMT.format(v))}))
        for v in lits]
    threads[0].start()
    assert first.wait(30)
    threads[1].start()
    gate = s.query_manager.batch_gate
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if sum(gate.queue_depth(fp) for fp in list(gate._templates)) >= 1:
            break
        time.sleep(0.01)
    release.set()
    for t in threads:
        t.join(60)
    h0 = counter("result_cache.hit")
    for v in lits:
        pd.testing.assert_frame_equal(s.sql(TOPN_FMT.format(v)), results[v])
    assert counter("result_cache.hit") >= h0 + 2, \
        "batched results did not populate the per-binding result cache"


# ---------------------------------------------------------------------------
# tenant attribution + server surface
# ---------------------------------------------------------------------------


def test_tenant_attribution_and_system_tables():
    qs = QueryServer({"tpch": CONN},
                     tenants=[TenantSpec("ana", weight=2.0),
                              TenantSpec("bot", max_concurrent=2)],
                     properties={"result_cache_enabled": False,
                                 "health_monitor": False})
    qs.execute("select count(*) c from orders", tenant="ana")
    qs.execute("select count(*) c from lineitem", tenant="bot")
    hist = qs.session.sql(
        "select tenant, state from query_history where tenant <> ''")
    assert {"ana", "bot"} <= set(hist["tenant"].tolist())
    ten = qs.session.sql(
        "select tenant, admitted, max_concurrent from tenants"
        " order by tenant")
    rows = {r["tenant"]: r for _, r in ten.iterrows()}
    assert rows["ana"]["admitted"] >= 1
    assert rows["bot"]["max_concurrent"] == 2
    # QueryInfo JSON carries the attribution too
    rec = next(i for i in qs.session.query_history if i.tenant == "ana")
    assert json.loads(rec.to_json())["tenant"] == "ana"


def test_server_prepared_surface_and_submit_poll():
    from presto_tpu.runtime.errors import UserError

    qs = QueryServer({"tpch": CONN},
                     properties={"result_cache_enabled": False,
                                 "health_monitor": False})
    name = qs.prepare("select count(*) c from orders where o_orderkey < ?",
                      tenant="ana")
    a = qs.execute_prepared(name, [512], tenant="ana")
    b = qs.execute_prepared(name, [4096], tenant="ana")
    assert int(a["c"][0]) < int(b["c"][0])
    # prepared handles are tenant-scoped: another tenant can neither
    # execute nor deallocate them through the shared session
    with pytest.raises(UserError):
        qs.execute_prepared(name, [512], tenant="bob")
    with pytest.raises(UserError):
        qs.deallocate(name, tenant="bob")
    qs.deallocate(name, tenant="ana")
    with pytest.raises(UserError):
        qs.execute_prepared(name, [512], tenant="ana")
    qid = qs.submit("select count(*) c from lineitem", tenant="bot")
    df = qs.result(qid, timeout_s=60)
    assert int(df["c"][0]) > 0
    page = qs.poll(qid)
    assert page["state"] == "FINISHED"
    assert page["columns"] == ["c"]


def test_server_shutdown_drains_and_refuses_new_work():
    from presto_tpu.runtime.errors import UserError

    qs = QueryServer({"tpch": CONN},
                     properties={"result_cache_enabled": False,
                                 "health_monitor": False})
    qs.execute("select count(*) c from orders")
    summary = qs.shutdown(drain_timeout_s=10)
    assert summary["drained"]
    assert summary["pool_reserved_bytes"] == 0
    with pytest.raises(UserError):
        qs.execute("select 1 a")
    with pytest.raises(UserError):
        qs.submit("select 1 a")


def test_http_round_trip():
    qs = QueryServer({"tpch": CONN},
                     tenants=[TenantSpec("web", weight=2.0)],
                     properties={"result_cache_enabled": False,
                                 "health_monitor": False})
    http = HttpFrontend(qs, port=0).start_background()
    base = f"http://127.0.0.1:{http.port}"
    try:
        req = urllib.request.Request(
            f"{base}/v1/statement",
            data=b"select count(*) c from orders where o_orderkey < 1000",
            headers={"X-Presto-Tenant": "web"}, method="POST")
        sub = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert sub["state"] == "QUEUED" and sub["nextUri"]
        page = {}
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            page = json.loads(urllib.request.urlopen(
                f"{base}{sub['nextUri']}", timeout=30).read())
            if page["state"] in ("FINISHED", "FAILED"):
                break
            time.sleep(0.05)
        assert page["state"] == "FINISHED", page
        assert page["columns"] == ["c"]
        assert page["data"][0][0] > 0
        # prepared surface over HTTP
        prep = json.loads(urllib.request.urlopen(urllib.request.Request(
            f"{base}/v1/prepared",
            data=json.dumps({"action": "prepare", "name": "h1",
                             "sql": "select count(*) c from orders"
                                    " where o_orderkey < ?"}).encode(),
            headers={"X-Presto-Tenant": "web"},
            method="POST"), timeout=30).read())
        assert prep["prepared"] == "h1"
        got = json.loads(urllib.request.urlopen(urllib.request.Request(
            f"{base}/v1/prepared",
            data=json.dumps({"action": "execute", "name": "h1",
                             "params": [512]}).encode(),
            headers={"X-Presto-Tenant": "web"},
            method="POST"), timeout=30).read())
        assert got["columns"] == ["c"]
        # metrics scrape parses (gate-7 exposition contract: # EOF last)
        mtext = urllib.request.urlopen(f"{base}/metrics",
                                       timeout=30).read().decode()
        assert mtext.splitlines()[-1] == "# EOF"
        assert "presto_tpu_query_completed_total" in mtext
        # tenant snapshot endpoint
        tens = json.loads(urllib.request.urlopen(
            f"{base}/v1/tenants", timeout=30).read())
        assert any(t["tenant"] == "web" and t["admitted"] >= 1
                   for t in tens)
    finally:
        http.shutdown()


def test_gate_abandoned_member_does_not_strand_the_queue():
    """A drained member that times out self-drops its ref; the leader's
    finish_lead must NOT drop it again — a double drop would pop the
    template entry out from under still-queued members, stranding them
    against a held executor lock (review regression)."""
    gate = TemplateBatchGate()
    fp = "tmpl"
    leader = gate.enqueue(fp, ((None, 1),))
    role, members = gate.lead_or_wait(fp, leader, 0.0)
    assert role == "lead" and members == [leader]
    drained = gate.enqueue(fp, ((None, 2),))
    queued = gate.enqueue(fp, ((None, 3),))
    # the leader drains `drained` into a second batch slot... simulate
    # by marking it drained out of the queue the way a leader would
    with gate._lock:
        gate._templates[fp]["queue"].remove(drained)
    # `drained` gives up waiting while the leader runs (self-drops)
    role2, _ = gate.lead_or_wait(fp, drained, 0.0)
    assert role2 == "timeout"
    # leader finishes its batch, which included the abandoned member
    gate.finish_lead(fp, leader, [leader, drained])
    # the still-queued member must be able to lead, not strand
    role3, members3 = gate.lead_or_wait(fp, queued, 0.0)
    assert role3 == "lead" and members3 == [queued]
    gate.finish_lead(fp, queued, members3)
    assert gate.queue_depth(fp) == 0


def test_server_submit_limit_rejects_floods():
    from presto_tpu.runtime.errors import ServerOverloaded

    qs = QueryServer({"tpch": CONN}, submit_limit=1,
                     properties={"result_cache_enabled": False,
                                 "health_monitor": False})
    # saturate the single pending slot with a record stuck QUEUED
    qs._queries["stuck"] = {"state": "QUEUED"}
    with pytest.raises(ServerOverloaded) as ei:
        qs.submit("select 1 a")
    assert ei.value.retryable and ei.value.retry_after_s > 0
    del qs._queries["stuck"]
    qid = qs.submit("select count(*) c from orders")
    assert int(qs.result(qid, timeout_s=60)["c"][0]) > 0


def test_tenant_cardinality_capped_by_overflow_lane():
    """The tenant header is client-controlled: past max_tenants,
    walk-in names pool into one shared __overflow__ lane instead of
    growing state and metric cardinality forever."""
    sched = FairScheduler(max_tenants=2)
    sched.release(sched.acquire("a"))
    sched.release(sched.acquire("b"))
    for name in ("c", "d", "e"):
        sched.release(sched.acquire(name))
    names = {r["tenant"] for r in sched.snapshot()}
    assert names == {"a", "b", "__overflow__"}, names
    over = next(r for r in sched.snapshot()
                if r["tenant"] == "__overflow__")
    assert over["admitted"] == 3


def test_submitted_query_polls_queued_while_scheduler_starved():
    """A submission starved at the fairness scheduler must poll as
    QUEUED (not RUNNING) until the fair slot is actually held."""
    qs = QueryServer({"tpch": CONN},
                     tenants=[TenantSpec("t", max_concurrent=1)],
                     properties={"result_cache_enabled": False,
                                 "health_monitor": False})
    token = qs.scheduler.acquire("t")  # hold the tenant's only slot
    try:
        qid = qs.submit("select count(*) c from orders", tenant="t")
        deadline = time.monotonic() + 5
        saw_queued = False
        while time.monotonic() < deadline:
            state = qs.poll(qid)["state"]
            assert state != "RUNNING", "starved submission shown RUNNING"
            if state == "QUEUED":
                saw_queued = True
                break
            time.sleep(0.01)
        assert saw_queued
    finally:
        qs.scheduler.release(token)
    assert int(qs.result(qid, timeout_s=60)["c"][0]) > 0
    assert qs.poll(qid)["state"] == "FINISHED"


def test_batched_dispatch_off_by_default_for_embedded_sessions():
    """The property gate: a plain Session never pays the batched
    path's extra compile — only the serving layer (or an explicit
    opt-in) turns it on."""
    s = make_session()
    assert s.prop("batched_dispatch") is False
    qs = QueryServer({"tpch": CONN},
                     properties={"health_monitor": False})
    assert qs.session.prop("batched_dispatch") is True
