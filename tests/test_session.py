"""Session runtime behaviors (query lifecycle, stats isolation,
session properties, the CLI statement loop).

Reference parity: per-query execution objects (SqlQueryExecution) —
per-query state like the stats recorder must not live on shared
machinery [SURVEY §3.1; round-1 advisor finding]; SystemSessionProperties
typed/validated per-session knobs [SURVEY §5.6]; presto-cli console
[SURVEY §2.1]."""

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.session import Session


def test_each_query_gets_a_fresh_executor(monkeypatch):
    s = Session({"tpch": TpchConnector(sf=0.01)})
    seen = []
    orig = Session._make_executor

    def spy(self):
        ex = orig(self)
        seen.append(ex)
        return ex

    monkeypatch.setattr(Session, "_make_executor", spy)
    s.sql("select count(*) c from nation")
    out = s.explain_analyze("select count(*) c from region")
    assert "rows" in out or "Output" in out
    assert len(seen) == 2
    assert seen[0] is not seen[1]
    # the session's template executor never carries a recorder
    assert s.executor.recorder is None


def test_nested_query_from_event_listener_keeps_outer_stats():
    """A listener that issues its own query mid-lifecycle must not
    clobber the outer query's recorded node stats."""
    s = Session({"tpch": TpchConnector(sf=0.01)})
    nested_df = []

    running = []

    class Listener:
        def query_created(self, info):
            pass

        def query_completed(self, info):
            if not running:  # re-entrancy guard
                running.append(True)
                nested_df.append(s.sql("select count(*) c from region"))

    s.add_event_listener(Listener())
    df, info = s.execute("select count(*) c from nation")
    assert int(df["c"][0]) == 25
    assert info.node_stats, "outer query lost its recorded stats"
    assert len(nested_df) == 1


# ---------------------------------------------------------------------------
# session properties (SURVEY §5.6)
# ---------------------------------------------------------------------------


def test_unknown_session_property_rejected():
    with pytest.raises(ValueError, match="unknown session property"):
        Session({"tpch": TpchConnector(sf=0.01)}, properties={"nope": 1})


def test_property_type_coercion_and_validation():
    s = Session(
        {"tpch": TpchConnector(sf=0.01)},
        properties={"gather_row_limit": "4096", "collect_node_stats": "true"},
    )
    assert s.prop("gather_row_limit") == 4096
    assert s.prop("collect_node_stats") is True
    with pytest.raises(ValueError, match="must be positive"):
        s.set_property("gather_row_limit", 0)
    with pytest.raises(ValueError, match="cannot interpret"):
        s.set_property("gather_row_limit", "abc")
    # 0 is legal where it means "disabled" (never broadcast)
    s.set_property("broadcast_join_row_limit", 0)
    assert s.prop("broadcast_join_row_limit") == 0


def test_show_session_lists_every_registered_property():
    from presto_tpu.runtime.properties import SESSION_PROPERTIES

    s = Session({"tpch": TpchConnector(sf=0.01)})
    rows = s.show_session()
    assert {r[0] for r in rows} == set(SESSION_PROPERTIES)
    assert all(r[2] for r in rows)  # every property is documented


def test_direct_group_limit_reaches_executor():
    s = Session(
        {"tpch": TpchConnector(sf=0.01)},
        properties={"direct_group_limit": 7},
    )
    assert s.executor.direct_group_limit == 7
    df = s.sql(
        "select l_returnflag, l_linestatus, count(*) c "
        "from lineitem group by l_returnflag, l_linestatus order by 1, 2"
    )
    assert df["c"].sum() > 0


def test_query_retries_rerun_failed_queries():
    s = Session(
        {"tpch": TpchConnector(sf=0.01)},
        properties={"query_retries": 2},
    )
    calls = []
    orig = Session._run_tracked

    def flaky(self, sql, plan, recorder, **kw):
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient device loss")
        return orig(self, sql, plan, recorder, **kw)

    Session._run_tracked = flaky
    try:
        df = s.sql("select count(*) c from nation")
    finally:
        Session._run_tracked = orig
    assert len(calls) == 3
    assert int(df["c"][0]) == 25


# ---------------------------------------------------------------------------
# CLI statement loop (presto-cli analog)
# ---------------------------------------------------------------------------


def test_cli_statements(capsys):
    from presto_tpu.__main__ import run_statement

    s = Session({"tpch": TpchConnector(sf=0.01)})
    assert run_statement(s, "select count(*) as c from nation;")
    out = capsys.readouterr().out
    assert "25" in out and "1 row" in out

    assert run_statement(s, "show tables;")
    assert "tpch.lineitem" in capsys.readouterr().out

    assert run_statement(s, "set session gather_row_limit = 1234;")
    assert s.prop("gather_row_limit") == 1234
    assert run_statement(s, "show session;")
    assert "gather_row_limit = 1234" in capsys.readouterr().out

    assert run_statement(s, "explain select * from nation;")
    assert "TableScan" in capsys.readouterr().out

    assert run_statement(s, "select no_such_column from nation;")
    assert "error:" in capsys.readouterr().err  # REPL survives bad SQL

    assert not run_statement(s, "quit;")


def test_cli_file_split_respects_quoted_semicolons():
    from presto_tpu.__main__ import split_statements

    stmts = split_statements(
        "select r_name from region where r_name like '%;%';\n"
        "select 1 ; select ';' from region"
    )
    assert stmts[0].strip() == "select r_name from region where r_name like '%;%'"
    assert stmts[1].strip() == "select 1"
    assert stmts[2].strip() == "select ';' from region"
