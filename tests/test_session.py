"""Session runtime behaviors (query lifecycle, stats isolation).

Reference parity: per-query execution objects (SqlQueryExecution) —
per-query state like the stats recorder must not live on shared
machinery [SURVEY §3.1; round-1 advisor finding]."""

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.session import Session


def test_each_query_gets_a_fresh_executor(monkeypatch):
    s = Session({"tpch": TpchConnector(sf=0.01)})
    seen = []
    orig = Session._make_executor

    def spy(self):
        ex = orig(self)
        seen.append(ex)
        return ex

    monkeypatch.setattr(Session, "_make_executor", spy)
    s.sql("select count(*) c from nation")
    out = s.explain_analyze("select count(*) c from region")
    assert "rows" in out or "Output" in out
    assert len(seen) == 2
    assert seen[0] is not seen[1]
    # the session's template executor never carries a recorder
    assert s.executor.recorder is None


def test_nested_query_from_event_listener_keeps_outer_stats():
    """A listener that issues its own query mid-lifecycle must not
    clobber the outer query's recorded node stats."""
    s = Session({"tpch": TpchConnector(sf=0.01)})
    nested_df = []

    running = []

    class Listener:
        def query_created(self, info):
            pass

        def query_completed(self, info):
            if not running:  # re-entrancy guard
                running.append(True)
                nested_df.append(s.sql("select count(*) c from region"))

    s.add_event_listener(Listener())
    df, info = s.execute("select count(*) c from nation")
    assert int(df["c"][0]) == 25
    assert info.node_stats, "outer query lost its recorded stats"
    assert len(nested_df) == 1
