"""UNION [ALL] and ROLLUP / CUBE / GROUPING SETS.

Reference parity: SetOperationNode planning + GroupIdNode-based
grouping sets [SURVEY §2.1 planner row]. Engine results are diffed
against pandas on the deterministic TPC-H fixture."""

import numpy as np
import pandas as pd
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.session import Session


@pytest.fixture(scope="module")
def env():
    conn = TpchConnector(sf=0.01)
    return Session({"tpch": conn}), conn


def test_union_all_bag_semantics(env):
    s, conn = env
    df = s.sql(
        "select n_regionkey k from nation union all select r_regionkey k from region"
    )
    # 25 nation rows + 5 region rows, duplicates kept
    assert len(df) == 30
    assert sorted(df["k"].tolist()).count(0) == 6  # 5 nations + 1 region


def test_union_distinct_dedups(env):
    s, _ = env
    df = s.sql(
        "select n_regionkey k from nation union select r_regionkey k from region "
        "order by k"
    )
    assert df["k"].tolist() == [0, 1, 2, 3, 4]


def test_union_type_coercion(env):
    s, _ = env
    # integer column unified with a double expression
    df = s.sql(
        "select n_nationkey v from nation where n_nationkey < 2 "
        "union all select 0.5 + r_regionkey v from region where r_regionkey = 0 "
        "order by v"
    )
    assert df["v"].tolist() == [0.0, 0.5, 1.0]


def test_union_across_different_dictionaries(env):
    s, conn = env
    df = s.sql(
        "select l_returnflag f, count(*) c from lineitem group by l_returnflag "
        "union all "
        "select l_linestatus f, count(*) c from lineitem group by l_linestatus "
        "order by f, c"
    )
    li = conn.table_pandas("lineitem")
    a = li.groupby("l_returnflag").size().rename("c").reset_index()
    a.columns = ["f", "c"]
    b = li.groupby("l_linestatus").size().rename("c").reset_index()
    b.columns = ["f", "c"]
    want = pd.concat([a, b]).sort_values(["f", "c"]).reset_index(drop=True)
    assert df["f"].tolist() == want["f"].tolist()
    assert df["c"].tolist() == want["c"].tolist()


def test_rollup_matches_pandas(env):
    s, conn = env
    df = s.sql(
        "select l_returnflag f, l_linestatus st, sum(l_quantity) q "
        "from lineitem group by rollup(l_returnflag, l_linestatus) "
        "order by f nulls last, st nulls last"
    )
    li = conn.table_pandas("lineitem")
    detail = li.groupby(["l_returnflag", "l_linestatus"])["l_quantity"].sum()
    per_flag = li.groupby("l_returnflag")["l_quantity"].sum()
    total = li["l_quantity"].sum()
    assert len(df) == len(detail) + len(per_flag) + 1
    # grand total row: both keys NULL
    last = df.iloc[-1]
    assert pd.isna(last["f"]) and pd.isna(last["st"])
    np.testing.assert_allclose(last["q"], total, rtol=1e-9)
    # a subtotal row
    sub = df[(df["f"] == "A") & (df["st"].isna())]
    np.testing.assert_allclose(sub["q"].iloc[0], per_flag["A"], rtol=1e-9)


def test_grouping_function(env):
    s, _ = env
    df = s.sql(
        "select grouping(n_regionkey) g, n_regionkey rk, count(*) c "
        "from nation group by rollup(n_regionkey) order by g, rk"
    )
    assert df["g"].tolist() == [0, 0, 0, 0, 0, 1]
    assert df["c"].tolist() == [5, 5, 5, 5, 5, 25]


def test_grouping_sets_explicit(env):
    s, _ = env
    df = s.sql(
        "select n_regionkey rk, count(*) c from nation "
        "group by grouping sets ((n_regionkey), ()) order by rk nulls last"
    )
    assert df["c"].tolist() == [5, 5, 5, 5, 5, 25]


def test_cube_set_count(env):
    s, conn = env
    df = s.sql(
        "select l_returnflag f, l_linestatus st, count(*) c "
        "from lineitem group by cube(l_returnflag, l_linestatus)"
    )
    li = conn.table_pandas("lineitem")
    n_pairs = len(li.groupby(["l_returnflag", "l_linestatus"]).size())
    n_flags = li["l_returnflag"].nunique()
    n_stats = li["l_linestatus"].nunique()
    assert len(df) == n_pairs + n_flags + n_stats + 1


def test_union_in_subquery_and_cte(env):
    s, _ = env
    df = s.sql(
        "with k as (select n_regionkey v from nation union all "
        "           select r_regionkey v from region) "
        "select v, count(*) c from k group by v order by v"
    )
    assert df["c"].tolist() == [6, 6, 6, 6, 6]
    df2 = s.sql(
        "select count(*) c from (select n_regionkey v from nation "
        "union select r_regionkey v from region) t"
    )
    assert int(df2["c"][0]) == 5


def test_intersect_and_except(env):
    s, _ = env
    df = s.sql(
        "select n_regionkey k from nation where n_regionkey < 3 "
        "intersect select r_regionkey k from region where r_regionkey > 1 "
        "order by k"
    )
    assert df["k"].tolist() == [2]
    df2 = s.sql(
        "select n_regionkey k from nation "
        "except select r_regionkey k from region where r_regionkey >= 2 "
        "order by k"
    )
    assert df2["k"].tolist() == [0, 1]


def test_intersect_binds_tighter_than_union(env):
    s, _ = env
    # A union (B intersect C): standard precedence
    df = s.sql(
        "select 0 k from region where r_regionkey = 4 "
        "union "
        "select n_regionkey k from nation where n_regionkey < 3 "
        "intersect select r_regionkey k from region where r_regionkey > 1 "
        "order by k"
    )
    assert df["k"].tolist() == [0, 2]


def test_intersect_over_dictionary_columns(env):
    s, conn = env
    df = s.sql(
        "select l_returnflag f from lineitem "
        "intersect select l_linestatus f from lineitem order by f"
    )
    li = conn.table_pandas("lineitem")
    want = sorted(set(li.l_returnflag) & set(li.l_linestatus))
    assert df["f"].tolist() == want


def test_intersect_all_rejected(env):
    s, _ = env
    with pytest.raises(Exception, match="ALL not supported"):
        s.sql("select 1 x intersect all select 1 x")
