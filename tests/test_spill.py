"""Planned hybrid-spill out-of-core tier (exec/spill.py) — larger-than-
HBM joins/aggs as a PLAN choice, not an OOM round-trip.

The contract under test:

- bit-identity: planned-hybrid, forced-grouped, and resident execution
  all return the same rows (joins, semi/anti, high-cardinality agg);
- a 4x-over-budget build runs with ZERO ladder rungs (the acceptance
  scenario — ``query.oom_degraded`` stays 0);
- lying stats still recover: a runtime OOM walks rung 1, which re-plans
  into hybrid with a shrunk resident set (``planned_hybrid`` rung-
  history entries are distinguishable from ``ladder`` ones);
- cold-partition overflow re-partitions recursively with a bounded
  depth and a TYPED loud failure at the cap;
- host-spill bytes are accounted against ``spill_host_budget_bytes`` /
  the process budget and drain to zero on success AND fault paths;
- the two-slot transfer pipeline genuinely double-buffers.
"""

import threading

import numpy as np
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.spill import (
    MAX_SPILL_RECURSION,
    expand_units,
    fit_resident,
    plan_spill,
    transfer_iter,
)
from presto_tpu.runtime import faults
from presto_tpu.runtime.errors import (
    DeviceOutOfMemory,
    PrestoError,
    SpillBudgetExceeded,
    SpillPartitionOverflow,
)
from presto_tpu.runtime.memory import global_host_spill_budget
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

SF = 0.005

Q3ISH = (
    "select o_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue "
    "from orders, lineitem where o_orderkey = l_orderkey "
    "and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15' "
    "group by o_orderkey order by revenue desc, o_orderkey limit 20"
)
SEMI = (
    "select c_custkey from customer where c_custkey in "
    "(select o_custkey from orders) order by c_custkey"
)
ANTI = (
    "select c_custkey from customer where c_custkey not in "
    "(select o_custkey from orders) order by c_custkey"
)
# join feeding a HIGH-CARDINALITY aggregation (the join defeats the
# fused leaf route, so both the join and agg strategy points execute)
HICARD_AGG = (
    "select l_orderkey, count(*) n, sum(l_extendedprice) s "
    "from lineitem join orders on l_orderkey = o_orderkey "
    "group by l_orderkey order by l_orderkey limit 100"
)

#: routes Q3ISH through hybrid (est/budget well under the grouped
#: ratio) — the orders build side at SF 0.005 is ~45 KB
HYBRID_BUDGET = 4096
#: est/budget over the hybrid ratio cap: nothing resident, fully
#: grouped — but the half-budget streamed-unit floor must still hold
#: one key's duplicate run (o_custkey repeats up to 25x at SF 0.005;
#: smaller budgets CORRECTLY refuse with SpillPartitionOverflow), so
#: the forcing budget is per build side: Q3ISH's filtered orders
#: build estimates ~17.5 KB (unique keys, 256 forces grouped), the
#: semi/anti o_custkey build ~30 KB with duplicate runs (448 forces
#: grouped while keeping a 224-byte unit floor)
GROUPED_BUDGETS = {Q3ISH: 256, SEMI: 448, ANTI: 448}


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=SF, units_per_split=1 << 12)


@pytest.fixture(scope="module")
def resident(conn):
    """Unbudgeted oracle results, one clean session per query."""
    s = Session({"tpch": conn})
    return {q: s.sql(q) for q in (Q3ISH, SEMI, ANTI, HICARD_AGG)}


def _delta(before: dict, name: str) -> float:
    return REGISTRY.snapshot().get(name, 0.0) - before.get(name, 0.0)


# ---------------------------------------------------------------------------
# the decision function
# ---------------------------------------------------------------------------


def test_plan_spill_decision_table():
    budget = 1 << 20
    assert plan_spill(budget // 2, budget).mode == "resident"
    d = plan_spill(4 * budget, budget)
    assert d.mode == "hybrid" and d.nbuckets == 8 and len(d.resident) >= 1
    assert d.explain() == f"hybrid({len(d.resident)}/8 resident)"
    g = plan_spill(100 * budget, budget)  # over HYBRID_MAX_RATIO
    assert g.mode == "grouped" and not g.resident
    assert "buckets" in g.explain()


def test_plan_spill_rung_shrinks_resident_set():
    budget = 1 << 20
    r0 = plan_spill(4 * budget, budget, oom_rung=0)
    r1 = plan_spill(4 * budget, budget, oom_rung=1)
    assert r1.mode == "hybrid"
    assert r1.nbuckets > r0.nbuckets  # doubled buckets
    assert r1.resident_budget < r0.resident_budget  # shrunk resident share
    # a LYING under-budget estimate at rung>0 still re-buckets for real
    lied = plan_spill(budget // 10, budget, oom_rung=1)
    assert lied.mode != "resident" and lied.nbuckets >= 2
    # deep rungs give up on residency entirely
    assert plan_spill(4 * budget, budget, oom_rung=3).mode == "grouped"


def test_plan_spill_hot_partition_leads_resident_set():
    d = plan_spill(8 << 20, 1 << 20, hot_partition=5)
    assert d.mode == "hybrid" and d.resident[0] == 5


def test_fit_resident_demotes_oversized_buckets():
    d = plan_spill(4 << 20, 1 << 20)
    # every planned-resident bucket is 10x the resident share: all demote
    res, acc = fit_resident(d, lambda b: 10 * d.resident_budget, 1)
    assert res == () and acc == 0
    res, acc = fit_resident(d, lambda b: 1, 1)
    assert res == d.resident and acc == len(d.resident)


# ---------------------------------------------------------------------------
# bit-identity differentials (hybrid vs grouped vs resident)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("q", [Q3ISH, SEMI, ANTI, HICARD_AGG])
def test_hybrid_bit_identical_to_resident(conn, resident, q):
    before = REGISTRY.snapshot()
    got = Session(
        {"tpch": conn},
        properties={"join_build_budget_bytes": HYBRID_BUDGET},
    ).sql(q)
    assert got.equals(resident[q]), "hybrid result differs from resident"
    assert _delta(before, "spill.planned_hybrid") >= 1
    assert _delta(before, "query.oom_degraded") == 0


@pytest.mark.parametrize("q", [Q3ISH, SEMI, ANTI])
def test_forced_grouped_bit_identical_to_resident(conn, resident, q):
    before = REGISTRY.snapshot()
    got = Session(
        {"tpch": conn},
        properties={"join_build_budget_bytes": GROUPED_BUDGETS[q]},
    ).sql(q)
    assert got.equals(resident[q]), "grouped result differs from resident"
    assert _delta(before, "spill.planned_grouped") >= 1
    assert _delta(before, "query.oom_degraded") == 0


def test_four_x_over_budget_runs_with_zero_rungs(conn, resident):
    """The acceptance scenario: a build ~4x over budget executes via
    planned hybrid — zero ladder rungs, zero failed compiles, rows
    bit-identical, host budget drained."""
    # orders build at SF 0.005 estimates ~45 KB -> ~4x an 11 KB budget
    before = REGISTRY.snapshot()
    s = Session({"tpch": conn},
                properties={"join_build_budget_bytes": 11 << 10})
    got = s.sql(Q3ISH)
    assert got.equals(resident[Q3ISH])
    assert _delta(before, "spill.planned_hybrid") >= 1
    assert _delta(before, "query.oom_degraded") == 0
    assert _delta(before, "spill.partitions_streamed") >= 1
    assert s.pool().reserved_bytes == 0
    assert global_host_spill_budget().reserved_bytes == 0


# ---------------------------------------------------------------------------
# lying stats: runtime OOM -> rung 1 re-plans INTO hybrid
# ---------------------------------------------------------------------------


def test_runtime_oom_replans_into_hybrid(conn):
    """The estimate said resident; a runtime OOM refuted it. Rung 1
    must re-plan into hybrid (shrunk resident set), not jump straight
    to fully-grouped — and the rung history must carry BOTH the ladder
    entry and the planned_hybrid decision it led to."""
    q = ("select n_name, count(*) c, sum(s_acctbal) b "
         "from supplier join nation on s_nationkey = n_nationkey "
         "group by n_name order by n_name")
    want = Session({"tpch": conn}).sql(q)
    s = Session({"tpch": conn})
    inj = faults.FaultInjector()
    inj.inject_oom("step.join_build", times=None)
    with faults.injected(inj):
        got = s.sql(q)
    assert got.equals(want)
    info = s.query_history[-1]
    assert info.oom_retries == 1
    kinds = [e.get("kind") for e in info.rung_history]
    assert "ladder" in kinds
    hybrids = [e for e in info.rung_history
               if e.get("kind") == "planned_hybrid"]
    assert hybrids, f"no planned_hybrid entry in {info.rung_history}"
    assert all(e["oom_rung"] == 1 for e in hybrids)


# ---------------------------------------------------------------------------
# partition overflow: bounded recursion, typed refusal
# ---------------------------------------------------------------------------


def _one_key_spill(rows: int):
    """A HostSpill whose single bucket holds ``rows`` copies of ONE key
    — re-partitioning can never split it."""
    from presto_tpu import BIGINT, Batch
    from presto_tpu.exec.grouped import HostSpill

    spill = HostSpill(1)
    batch = Batch.from_numpy(
        {"k": np.full(rows, 7, np.int64)}, {"k": BIGINT}, capacity=rows)
    spill.append(batch, np.zeros(rows, np.int64))
    return spill


def _hash_ids(batch, modulus):
    import jax.numpy as jnp

    from presto_tpu.ops.hashing import partition_ids

    return np.asarray(
        partition_ids([batch["k"].data.astype(jnp.int64)], modulus))


def test_partition_overflow_recursion_is_bounded_and_typed():
    spill = _one_key_spill(100)
    before = REGISTRY.snapshot()
    with pytest.raises(SpillPartitionOverflow) as ei:
        expand_units(spill, None, [0], unit_budget=64, row_bytes=8,
                     build_ids=_hash_ids)
    assert "recursive splits" in str(ei.value)
    from presto_tpu.runtime.errors import error_code

    assert error_code(ei.value) == "SPILL_PARTITION_OVERFLOW"
    # each attempted split was LOUD, and the depth cap bounded them
    assert _delta(before, "spill.partition_overflow") == MAX_SPILL_RECURSION


def test_splittable_overflow_bucket_streams_in_units():
    """Distinct keys DO split: an oversized bucket expands into several
    under-budget units covering every row exactly once."""
    from presto_tpu import BIGINT, Batch
    from presto_tpu.exec.grouped import HostSpill

    spill = HostSpill(1)
    batch = Batch.from_numpy(
        {"k": np.arange(256, dtype=np.int64)}, {"k": BIGINT}, capacity=256)
    spill.append(batch, np.zeros(256, np.int64))
    units = expand_units(spill, None, [0], unit_budget=512, row_bytes=8,
                         build_ids=_hash_ids)
    assert len(units) > 1
    assert sum(u.build.bucket_rows(u.bucket) for u in units) == 256
    for u in units:
        rows = u.build.bucket_rows(u.bucket)
        assert rows * 8 <= 512 or rows <= 16


# ---------------------------------------------------------------------------
# host-budget accounting: success AND fault paths drain to zero
# ---------------------------------------------------------------------------


def test_spill_host_budget_exceeded_is_typed_and_loud(conn):
    """A session-scoped host budget too small for the spill fails with
    the TYPED error naming the property — and leaks nothing."""
    s = Session({"tpch": conn}, properties={
        "join_build_budget_bytes": HYBRID_BUDGET,
        "spill_host_budget_bytes": 2048,
    })
    with pytest.raises(PrestoError) as ei:
        s.sql(Q3ISH)
    assert isinstance(ei.value, SpillBudgetExceeded)
    assert "spill_host_budget_bytes" in str(ei.value)
    info = s.query_history[-1]
    assert info.state == "FAILED"
    assert info.error_code == "SPILL_BUDGET_EXCEEDED"
    assert s.pool().reserved_bytes == 0
    assert global_host_spill_budget().reserved_bytes == 0


def test_mid_spill_fault_drains_pool_and_host_budget(conn):
    """A backend OOM at the transfer fault site mid-spill: typed
    surface, pool balance zero, host reservation zero, exactly one
    complete flight record."""
    s = Session({"tpch": conn}, properties={
        "join_build_budget_bytes": HYBRID_BUDGET,
        "oom_ladder_max": 0,
    })
    inj = faults.FaultInjector()
    inj.inject_oom("step.spill_transfer", times=None)
    with faults.injected(inj):
        with pytest.raises(DeviceOutOfMemory):
            s.sql(Q3ISH)
    assert inj.fired_at("step.spill_transfer") >= 1
    info = s.query_history[-1]
    assert info.state == "FAILED"
    assert s.pool().reserved_bytes == 0
    assert global_host_spill_budget().reserved_bytes == 0
    recs = [r for r in s.flight.records() if r.query_id == info.query_id]
    assert len(recs) == 1 and recs[0].plan_render and recs[0].spans


def test_success_path_drains_host_budget(conn, resident):
    budget = global_host_spill_budget()
    got = Session(
        {"tpch": conn},
        properties={"join_build_budget_bytes": HYBRID_BUDGET},
    ).sql(Q3ISH)
    assert got.equals(resident[Q3ISH])
    assert budget.reserved_bytes == 0
    assert budget.peak_bytes > 0  # the spill actually reserved


# ---------------------------------------------------------------------------
# double-buffered transfer pipeline
# ---------------------------------------------------------------------------


def test_transfer_iter_double_buffers(monkeypatch):
    """Two loads must genuinely be in flight at once: the first two
    items rendezvous on a barrier that only concurrent workers can
    satisfy (a serial loop would deadlock it — the timeout is the
    failure signal)."""
    monkeypatch.setenv("PRESTO_TPU_PREFETCH", "1")
    barrier = threading.Barrier(2)

    def load(i):
        if i < 2:
            barrier.wait(timeout=30)
        return i * 10

    out = list(transfer_iter(load, range(4)))
    assert out == [(0, 0), (1, 10), (2, 20), (3, 30)]


def test_transfer_iter_serial_without_prefetch(monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_PREFETCH", "0")
    order = []

    def load(i):
        order.append(i)
        return i

    out = list(transfer_iter(load, range(3)))
    assert out == [(0, 0), (1, 1), (2, 2)] and order == [0, 1, 2]
