"""SQL-surface gap ledger (ISSUE-17 satellite).

The TPC-H sweep passes all 22 queries, so coverage pressure moves to
the surface OUTSIDE the benchmark. This file pins both sides of that
frontier with one machine-readable registry:

- ``GAPS``: features the engine does NOT support today. Each entry
  records the probe SQL, the exact typed error class and message
  fragment, and a structured reason (JSON in the xfail reason — CI
  tooling can diff the ledger across versions). The xfails are STRICT:
  implementing a feature turns its probe into an XPASS failure, which
  forces the ledger entry to be retired in the same change — the
  registry can never go quietly stale.
- The supported-surface tests: the nearest shapes that DO work
  (correlated subqueries, unbounded window frames, CTE reuse, set
  ops) keep working and keep returning CORRECT rows vs a pandas
  oracle — a gap may be a gap, but its neighbors must not regress.

Every gap must fail TYPED (``PrestoError``): "not supported" is a
user-facing contract, never a stack trace.
"""

import json

import pandas as pd
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.errors import PrestoError, UserError
from presto_tpu.runtime.session import Session
from presto_tpu.sql.lexer import LexError
from presto_tpu.sql.parser import ParseError

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=0.002)


@pytest.fixture(scope="module")
def s(conn):
    return Session({"tpch": conn})


# ---------------------------------------------------------------------------
# the gap ledger
# ---------------------------------------------------------------------------

#: feature -> {sql, raises, match, notes}. ``raises``/``match`` pin the
#: TYPED failure; ``notes`` is the human hint a future implementer
#: reads first. Keys are stable identifiers (they appear in xfail
#: reasons and CI diffs) — rename only when the feature scope changes.
GAPS = {
    "window_frame_bounded": {
        "sql": ("select o_orderkey, sum(o_totalprice) over ("
                "order by o_orderkey rows between 2 preceding and "
                "current row) s from orders limit 5"),
        "raises": ParseError,
        "match": "expected UNBOUNDED",
        "notes": ("only UNBOUNDED PRECEDING .. CURRENT ROW frames "
                  "parse; bounded ROWS/RANGE frames need a sliding "
                  "window plan shape"),
    },
    "window_frame_following": {
        "sql": ("select o_orderkey, sum(o_totalprice) over ("
                "order by o_orderkey rows between current row and "
                "unbounded following) s from orders limit 5"),
        "raises": ParseError,
        "match": "expected UNBOUNDED",
        "notes": "frames anchored at CURRENT ROW start do not parse",
    },
    "window_ntile": {
        "sql": ("select o_orderkey, ntile(4) over ("
                "order by o_totalprice) n from orders limit 5"),
        "raises": PrestoError,
        "match": "unknown window function ntile",
        "notes": ("rank/dense_rank/row_number/lag/lead/first_value/"
                  "last_value exist; ntile needs bucket arithmetic "
                  "over the partition ordinal"),
    },
    "recursive_cte": {
        "sql": ("with recursive r(n) as (select 1 union all "
                "select n+1 from r where n < 5) "
                "select count(*) c from r"),
        "raises": ParseError,
        "match": "expected AS",
        "notes": ("WITH RECURSIVE (and CTE column aliases) do not "
                  "parse; fixpoint iteration has no plan shape"),
    },
    "values_constructor": {
        "sql": "select * from (values (1, 'a'), (2, 'b')) t(x, y)",
        "raises": ParseError,
        "match": "expected",
        "notes": "inline VALUES relations do not parse",
    },
    "array_type": {
        "sql": "select array[1, 2, 3] a",
        "raises": LexError,
        "match": "unexpected character",
        "notes": ("no ARRAY type: '[' does not tokenize; UNNEST and "
                  "array functions are out with it"),
    },
    "lateral_join": {
        "sql": ("select o_orderkey from orders cross join lateral "
                "(select max(l_quantity) q from lineitem "
                "where l_orderkey = o_orderkey) t limit 5"),
        "raises": ParseError,
        "match": "trailing input",
        "notes": ("LATERAL derived tables do not parse; correlated "
                  "scalar subqueries in WHERE cover the common case"),
    },
    "quantified_comparison": {
        "sql": ("select count(*) c from orders where o_totalprice > "
                "all (select avg(o_totalprice) from orders)"),
        "raises": ParseError,
        "match": "quantified comparisons not supported",
        "notes": ("> ALL / > ANY(SOME) are rejected at parse; "
                  "scalar-subquery comparison covers single-row "
                  "producers"),
    },
    "concat_dictionary_column": {
        "sql": "select o_orderpriority || '-x' v from orders limit 3",
        "raises": PrestoError,
        "match": "string operands",
        "notes": ("|| works on plain VARCHAR (o_comment) but rejects "
                  "dictionary-encoded columns — concat needs a "
                  "decode-then-concat path"),
    },
}


def _xfail_reason(name: str) -> str:
    g = GAPS[name]
    return json.dumps({
        "feature": name,
        "error": g["raises"].__name__,
        "match": g["match"],
        "notes": g["notes"],
    }, sort_keys=True)


@pytest.mark.parametrize(
    "name",
    [pytest.param(n, marks=pytest.mark.xfail(
        reason=_xfail_reason(n), raises=GAPS[n]["raises"], strict=True))
     for n in sorted(GAPS)],
)
def test_gap(name, s):
    """Each probe must keep failing (typed) until the feature lands —
    then the strict xfail XPASSes and the ledger entry must go."""
    s.sql(GAPS[name]["sql"])


@pytest.mark.parametrize("name", sorted(GAPS))
def test_gap_failure_is_typed_with_recorded_message(name, s):
    """The ledger's error class and message fragment stay accurate —
    and every rejection is a PrestoError, never a bare stack trace."""
    g = GAPS[name]
    with pytest.raises(g["raises"], match=g["match"]) as ei:
        s.sql(g["sql"])
    assert isinstance(ei.value, PrestoError), (
        f"{name}: surface rejection leaked an untyped "
        f"{type(ei.value).__name__}")


def test_ledger_entries_are_well_formed():
    """The registry stays machine-readable: every entry serializes to
    the JSON shape CI tooling diffs, and the recorded class is typed."""
    for name, g in GAPS.items():
        assert set(g) == {"sql", "raises", "match", "notes"}, name
        assert issubclass(g["raises"], PrestoError), name
        parsed = json.loads(_xfail_reason(name))
        assert parsed["feature"] == name


# ---------------------------------------------------------------------------
# the supported frontier: nearest working shapes stay correct
# ---------------------------------------------------------------------------


def test_correlated_scalar_subquery_matches_oracle(s, conn):
    df = s.sql(
        "select o_orderkey k from orders o where o_totalprice > "
        "(select avg(l_extendedprice) from lineitem l "
        "where l_orderkey = o_orderkey) order by o_orderkey")
    o = conn.table_pandas("orders")
    li = conn.table_pandas("lineitem")
    avg = li.groupby("l_orderkey")["l_extendedprice"].mean()
    want = sorted(
        int(k) for k, p in zip(o["o_orderkey"], o["o_totalprice"])
        if k in avg.index and float(p) > float(avg[k]))
    assert [int(v) for v in df["k"]] == want


def test_correlated_exists_matches_oracle(s, conn):
    df = s.sql(
        "select o_orderkey k from orders o where exists "
        "(select 1 from lineitem l where l_orderkey = o_orderkey "
        "and l_quantity > 45) order by o_orderkey")
    li = conn.table_pandas("lineitem")
    want = sorted(
        int(v) for v in
        li.loc[li["l_quantity"] > 45, "l_orderkey"].unique())
    assert [int(v) for v in df["k"]] == want


def test_unbounded_window_frame_matches_oracle(s, conn):
    """The frame shape that DOES parse — running sum over UNBOUNDED
    PRECEDING .. CURRENT ROW — computes the cumulative sum."""
    df = s.sql(
        "select o_orderkey k, sum(o_totalprice) over ("
        "order by o_orderkey rows between unbounded preceding and "
        "current row) s from orders order by o_orderkey")
    o = conn.table_pandas("orders").sort_values("o_orderkey")
    want = o["o_totalprice"].astype(float).cumsum()
    assert len(df) == len(o)
    pd.testing.assert_series_equal(
        df["s"].astype(float).reset_index(drop=True),
        want.reset_index(drop=True),
        check_names=False, rtol=1e-4)


def test_cte_reused_twice_matches_oracle(s, conn):
    """One CTE consumed by both sides of a self-join — the reuse shape
    the WITH clause exists for."""
    df = s.sql(
        "with t as (select o_custkey k, sum(o_totalprice) p "
        "from orders group by o_custkey) "
        "select count(*) c from t a, t b where a.k = b.k and a.p > b.p")
    assert int(df["c"][0]) == 0  # a.p > b.p is irreflexive on a.k = b.k
    o = conn.table_pandas("orders")
    df2 = s.sql(
        "with t as (select o_custkey k, sum(o_totalprice) p "
        "from orders group by o_custkey) "
        "select count(*) c from t a, t b where a.k = b.k")
    assert int(df2["c"][0]) == o["o_custkey"].nunique()


def test_set_operations_match_oracle(s, conn):
    o = conn.table_pandas("orders")
    c = conn.table_pandas("customer")
    both = s.sql("select o_custkey k from orders "
                 "intersect select c_custkey from customer")
    want_i = set(o["o_custkey"]) & set(c["c_custkey"])
    assert set(int(v) for v in both["k"]) == {int(v) for v in want_i}
    only = s.sql("select c_custkey k from customer "
                 "except select o_custkey from orders")
    want_e = set(c["c_custkey"]) - set(o["o_custkey"])
    assert set(int(v) for v in only["k"]) == {int(v) for v in want_e}


def test_window_rank_and_lag_match_oracle(s, conn):
    df = s.sql(
        "select o_orderkey k, "
        "rank() over (partition by o_orderstatus "
        "order by o_totalprice desc) r, "
        "lag(o_totalprice) over (order by o_orderkey) p "
        "from orders order by o_orderkey")
    o = conn.table_pandas("orders").sort_values("o_orderkey")
    want_rank = o.groupby("o_orderstatus")["o_totalprice"].rank(
        method="min", ascending=False)
    assert [int(v) for v in df["r"]] == [int(v) for v in want_rank]
    want_lag = o["o_totalprice"].astype(float).shift(1)
    got_lag = df["p"].astype(float).reset_index(drop=True)
    pd.testing.assert_series_equal(
        got_lag, want_lag.reset_index(drop=True),
        check_names=False, rtol=1e-4)


def test_grouping_sets_and_rollup(s, conn):
    o = conn.table_pandas("orders")
    for q in (
        "select o_orderstatus g, count(*) c from orders "
        "group by grouping sets ((o_orderstatus), ())",
        "select o_orderstatus g, count(*) c from orders "
        "group by rollup (o_orderstatus)",
    ):
        df = s.sql(q)
        # per-status rows plus the grand-total row
        assert len(df) == o["o_orderstatus"].nunique() + 1
        assert int(df["c"].max()) <= len(o)
        assert int(df["c"].sum()) == 2 * len(o)


def test_gap_probe_never_corrupts_the_session(s):
    """A rejected probe leaves the session fully usable (parse errors
    must not wedge shared state) — run one gap then a clean query."""
    with pytest.raises(UserError):
        s.sql(GAPS["recursive_cte"]["sql"])
    df = s.sql("select count(*) c from orders")
    assert int(df["c"][0]) > 0
