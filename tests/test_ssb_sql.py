"""Engine-vs-oracle differential tests for the 13 SSB queries plus the
config-5 LIKE/substring variants (both the jnp and Pallas string-kernel
routes) [SURVEY §4, §6 config 5]."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from presto_tpu.connectors.ssb import SsbConnector
from presto_tpu.connectors.ssb.queries import QUERIES
from presto_tpu.oracle.ssb_oracle import ORACLES
from presto_tpu.runtime.session import Session

from tests.test_tpch_sql import compare

SF = 0.02


@pytest.fixture(scope="module")
def env():
    conn = SsbConnector(sf=SF, units_per_split=1 << 15)
    session = Session({"ssb": conn})
    tables = {name: conn.table_pandas(name) for name in conn.tables()}
    return session, tables


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_ssb_query_matches_oracle(env, name):
    session, tables = env
    got = session.sql(QUERIES[name])
    want = ORACLES[name](tables)
    if name != "q3_4":  # spec drill-down: legitimately empty at test SF
        assert len(want) > 0, f"{name}: oracle returned no rows"
    compare(got, want, name)


@pytest.mark.parametrize("name", ["q_like_part", "q_like_phone"])
def test_ssb_like_queries_via_pallas(env, name, monkeypatch):
    """The same LIKE queries routed through the Pallas kernels
    (interpret mode on CPU; compiled on TPU)."""
    monkeypatch.setenv("PRESTO_TPU_PALLAS", "1")
    session, tables = env
    compare(session.sql(QUERIES[name]), ORACLES[name](tables), f"pallas_{name}")


def test_ssb_distributed(env):
    """One query per SSB flight family over the 8-device mesh, plus a
    4-device flight-3 run (mesh-shape metamorphic)."""
    from presto_tpu.parallel.mesh import make_mesh

    session, tables = env
    dist = Session({"ssb": session.catalog.connector("ssb")}, mesh=make_mesh(8))
    for name in ["q1_1", "q2_1", "q3_2", "q4_2"]:
        compare(dist.sql(QUERIES[name]), ORACLES[name](tables), f"dist_{name}")
    dist4 = Session({"ssb": session.catalog.connector("ssb")},
                    mesh=make_mesh(4))
    for name in ["q3_1", "q4_1"]:
        compare(dist4.sql(QUERIES[name]), ORACLES[name](tables),
                f"dist4_{name}")
