"""Streaming ingestion (presto_tpu/stream, ISSUE-17): O(micro-batch)
appends on the memory connector, INCREMENTAL stats maintenance, version
epochs, and SCOPED cache invalidation.

The contract under test:

- Appends encode only the micro-batch (the full table is never
  re-inferred or re-scanned), yet the stored min/max/ndv/null_fraction
  after N appends are BIT-identical to a from-scratch recompute over
  the concatenated rows — so narrow physical storage and fused
  leaf-route admission decide the same either way.
- Every write bumps the table's monotone version epoch; a zero-row
  batch bumps nothing and invalidates nothing.
- Invalidation is SCOPED: an append to table A drops result-cache and
  plan-stats entries whose fingerprints reference A, and nothing else.
"""

import numpy as np
import pandas as pd
import pytest

from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runtime.errors import UserError
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session
from presto_tpu.stream import StreamWriter


def counter(name: str) -> float:
    return REGISTRY.snapshot().get(name, 0.0)


def _batches(seed: int, n_batches: int = 5, rows: int = 40):
    """Deterministic micro-batches over every streamable column shape:
    ints with NULLs, doubles, dates, bools, and a VARCHAR column whose
    later batches introduce unseen strings (dictionary growth)."""
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        k = rng.integers(-1000, 1000, rows).astype(np.int64)
        nullable = pd.array(k.copy(), dtype="Int64")
        nullable[rng.random(rows) < 0.3] = pd.NA
        out.append(pd.DataFrame({
            "k": k,
            "n": nullable,
            "x": rng.normal(size=rows),
            "d": pd.to_datetime("2026-01-01")
            + pd.to_timedelta(rng.integers(0, 400, rows), unit="D"),
            "b": rng.random(rows) < 0.5,
            # batch b draws from a vocabulary that keeps growing, so
            # appends exercise both the in-dictionary fast path and
            # the ordered-code remap
            "s": [f"tag-{v}" for v in rng.integers(0, 4 + 3 * b, rows)],
        }))
    return out


STATS_COLS = ("k", "n", "d")  # INTEGER/BIGINT/DATE kinds carry stats


def test_incremental_stats_bit_identical_to_recompute():
    """ISSUE-17 satellite 1: after N appends, stored stats equal a
    from-scratch ``create_table`` over the concatenated rows — exact
    equality, not approximate, because leaf-route admission and narrow
    storage key on these numbers."""
    batches = _batches(seed=7)
    inc = MemoryConnector()
    inc.create_table("t", batches[0])
    for b in batches[1:]:
        inc.append("t", b)
    scratch = MemoryConnector()
    scratch.create_table("t", pd.concat(batches, ignore_index=True))
    for c in STATS_COLS:
        got, want = inc.stats("t", c), scratch.stats("t", c)
        assert want is not None, c
        assert got.ndv == want.ndv, c
        assert got.min_value == want.min_value, c
        assert got.max_value == want.max_value, c
        assert got.null_fraction == want.null_fraction, c
    # the merged physical schema (narrowing decisions) agrees too
    assert repr(inc.physical_schema("t")) == repr(scratch.physical_schema("t"))


def test_appended_table_scans_identical_to_recreated():
    """Row data (every type, NULL masks, dictionary codes) after
    appends matches a from-scratch store of the same rows."""
    batches = _batches(seed=11)
    inc = MemoryConnector()
    inc.create_table("t", batches[0])
    for b in batches[1:]:
        inc.append("t", b)
    scratch = MemoryConnector()
    scratch.create_table("t", pd.concat(batches, ignore_index=True))
    pd.testing.assert_frame_equal(
        inc.table_pandas("t"), scratch.table_pandas("t"), check_exact=True)
    assert counter("stream.dict_rebuilds") > 0 or True  # growth happened
    # dictionary growth actually occurred (the test would silently
    # weaken if the vocabulary schedule stopped introducing strings)
    assert len(inc.dictionaries("t")["s"].values) > 4


def test_append_is_o_micro_batch_not_o_table():
    """The append path must never fall back to the full re-encode:
    ``_built_entry`` (type re-inference over ALL rows) runs only for
    create/CTAS, and appending never re-infers old rows."""
    conn = MemoryConnector()
    batches = _batches(seed=3, n_batches=4)
    conn.create_table("t", batches[0])
    calls = []
    orig = conn._built_entry
    conn._built_entry = lambda df: (calls.append(len(df)), orig(df))[1]
    for b in batches[1:]:
        conn.append("t", b)
    assert calls == [], "append fell back to the full-table re-encode"
    assert conn.row_count("t") == sum(len(b) for b in batches)


def test_epochs_monotone_and_zero_row_noop():
    conn = MemoryConnector()
    df = pd.DataFrame({"k": np.arange(5, dtype=np.int64)})
    assert conn.table_epoch("t") == 0
    conn.create_table("t", df)
    assert conn.table_epoch("t") == 1
    conn.append("t", df)
    assert conn.table_epoch("t") == 2
    # zero-row micro-batch: no work, no epoch bump, no invalidation
    assert conn.append("t", df.iloc[:0]) == 0
    assert conn.table_epoch("t") == 2
    # drop bumps (a subscription must not mistake recreate for fresh)
    conn.drop_table("t")
    assert conn.table_epoch("t") == 3
    conn.create_table("t", df)
    assert conn.table_epoch("t") == 4
    assert conn.epochs()["t"] == 4


def test_append_rejects_schema_and_type_mismatch():
    conn = MemoryConnector()
    conn.create_table("t", pd.DataFrame({"k": np.arange(5, dtype=np.int64)}))
    with pytest.raises(KeyError):
        conn.append("missing", pd.DataFrame({"k": [1]}))
    with pytest.raises(UserError):
        conn.append("t", pd.DataFrame({"other": [1]}))
    with pytest.raises(UserError):  # DOUBLE into BIGINT never narrows
        conn.append("t", pd.DataFrame({"k": [1.5]}))
    assert conn.table_epoch("t") == 1, "failed append must not bump"


def test_scoped_invalidation_append_to_a_keeps_b():
    """ISSUE-17 satellite 2: an append to table A evicts cached
    results/plan-stats for A and ONLY for A — table B's entries
    survive and still hit."""
    conn = MemoryConnector()
    s = Session({"memory": conn}, properties={"result_cache_enabled": True,
                                              "collect_node_stats": True})
    w = StreamWriter(s)
    w.append("a", pd.DataFrame({"v": np.arange(10, dtype=np.int64)}))
    w.append("b", pd.DataFrame({"v": np.arange(20, dtype=np.int64)}))
    qa, qb = "select sum(v) s from a", "select sum(v) s from b"
    s.sql(qa), s.sql(qb)  # populate both
    hit0 = counter("result_cache.hit")
    s.sql(qb)
    assert counter("result_cache.hit") == hit0 + 1  # warm before append

    def ps_tables(store):
        return [{t for t, _v in e.versions} for e in store.entries()]

    before = ps_tables(s.plan_stats)
    assert any("a" in ts for ts in before), "plan-stats missed query A"
    assert any("b" in ts for ts in before), "plan-stats missed query B"

    w.append("a", pd.DataFrame({"v": np.arange(10, 15, dtype=np.int64)}))

    # B still hits: the append to A did not touch its entry
    hit1 = counter("result_cache.hit")
    s.sql(qb)
    assert counter("result_cache.hit") == hit1 + 1, (
        "append to A evicted B's result-cache entry (scoped "
        "invalidation broken)")
    # plan-stats: A's entries dropped eagerly, B's survived
    after = ps_tables(s.plan_stats)
    assert not any("a" in ts for ts in after), "A's plan-stats survived"
    assert any("b" in ts for ts in after), "B's plan-stats were evicted"
    # A re-executes fresh (not served stale from cache) and is correct
    hit2 = counter("result_cache.hit")
    df = s.sql(qa)
    assert counter("result_cache.hit") == hit2, "stale hit on appended table"
    assert int(df["s"][0]) == int(np.arange(15).sum())


def test_stream_writer_creates_then_appends():
    conn = MemoryConnector()
    s = Session({"memory": conn})
    w = StreamWriter(s)
    a0 = counter("stream.appends")
    r1 = w.append("t", pd.DataFrame({"v": np.arange(3, dtype=np.int64)}))
    assert r1.created and r1.rows == 3 and r1.epoch == 1
    r2 = w.append("t", pd.DataFrame({"v": np.arange(3, 7, dtype=np.int64)}))
    assert not r2.created and r2.total_rows == 7 and r2.epoch == 2
    assert counter("stream.appends") == a0 + 2
    assert w.epoch("t") == 2
    df = s.sql("select count(*) c, max(v) m from t")
    assert int(df["c"][0]) == 7 and int(df["m"][0]) == 6


def test_stream_writer_rejects_unstreamable_catalog():
    from presto_tpu.connectors.tpch import TpchConnector

    s = Session({"tpch": TpchConnector(sf=0.001)})
    with pytest.raises(UserError, match="not streamable"):
        StreamWriter(s, "tpch")
    with pytest.raises(UserError, match="unknown catalog"):
        StreamWriter(s, "nope")


def test_sql_insert_rides_the_append_path():
    """INSERT INTO goes through the same O(batch) path: epoch bumps,
    stats stay exact."""
    conn = MemoryConnector()
    s = Session({"memory": conn})
    s.sql("create table t as select 1 as v")
    e0 = conn.table_epoch("t")
    s.sql("insert into t select 2 as v")
    assert conn.table_epoch("t") == e0 + 1
    st = conn.stats("t", "v")
    assert (st.min_value, st.max_value, st.ndv) == (1, 2, 2.0)
