"""Streaming-scan semantics (round-2 VERDICT item 2).

The local executor flows batches as replayable lazy streams: the scan
yields one device batch per split, pipeline breakers fold them into
bounded state, and capacity-overflow retries REPLAY the stream
(regenerate) instead of holding everything resident. These tests pin
the three load-bearing behaviors: laziness, bounded residency, and
replay-correct retries.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.session import Session


def _session(sf=0.01, units=1 << 12):
    # many small splits so streaming has something to stream
    return Session({"tpch": TpchConnector(sf=sf, units_per_split=units)})


def test_scan_is_lazy_and_streams_splits(monkeypatch):
    s = _session()
    conn = s.catalog.connector("tpch")
    calls = []
    real = conn.scan

    def spy(split, cols=None, capacity=None):
        calls.append(split.chunk)
        return real(split, cols, capacity)

    monkeypatch.setattr(conn, "scan", spy)
    stream = s.executor._exec(
        s.plan("select l_orderkey from lineitem").child, {}
    )
    assert calls == [], "scan must not run until the stream is drained"
    it = iter(stream)
    next(it)
    assert len(calls) == 1, "exactly one split scanned per batch pulled"


def test_streamed_aggregation_matches_oracle():
    """Q1 over many small splits (the streaming fold) must match the
    pandas oracle over the same connector's data."""
    s = _session(units=1 << 11)  # ~30 splits
    got = s.sql(
        "select l_returnflag, l_linestatus, sum(l_quantity) q, count(*) c "
        "from lineitem where l_shipdate <= date '1998-09-02' "
        "group by l_returnflag, l_linestatus "
        "order by l_returnflag, l_linestatus"
    )
    li = s.catalog.connector("tpch").table_pandas("lineitem")
    m = li[li.l_shipdate <= np.datetime64("1998-09-02")]
    want = (
        m.groupby(["l_returnflag", "l_linestatus"])
        .agg(q=("l_quantity", "sum"), c=("l_quantity", "size"))
        .reset_index()
    )
    np.testing.assert_allclose(got["q"].to_numpy(), want["q"].to_numpy())
    np.testing.assert_array_equal(got["c"].to_numpy(), want["c"].to_numpy())


def test_overflow_retry_replays_the_stream(monkeypatch):
    """A sort-strategy group overflow mid-stream retries at doubled
    capacity by REPLAYING the scan; a plain generator would come back
    empty and silently drop rows (the bug class this design avoids)."""
    # lie about the expected row count so max_groups starts far too
    # small and the first attempt overflows after consuming batches
    import presto_tpu.plan.bounds as B

    monkeypatch.setattr(B, "estimate_rows", lambda node, cat: 16)

    s = _session(units=1 << 11)
    got = s.sql("select l_orderkey, count(*) c from lineitem "
                "group by l_orderkey order by l_orderkey")
    monkeypatch.undo()
    li = s.catalog.connector("tpch").table_pandas("lineitem", ["l_orderkey"])
    want = (
        li.groupby("l_orderkey").size().rename("c").reset_index()
        .sort_values("l_orderkey").reset_index(drop=True)
    )
    np.testing.assert_array_equal(
        got["l_orderkey"].to_numpy(), want["l_orderkey"].to_numpy()
    )
    np.testing.assert_array_equal(got["c"].to_numpy(), want["c"].to_numpy())


def test_join_probe_streams_and_matches_oracle():
    """The probe side streams batch-by-batch; results must match the
    pandas merge over the same connector's data."""
    s = _session(units=1 << 11)
    q = ("select o_orderkey, l_quantity from orders, lineitem "
         "where o_orderkey = l_orderkey and o_orderdate < date '1993-01-01' "
         "order by o_orderkey, l_quantity limit 50")
    got = s.sql(q)
    conn = s.catalog.connector("tpch")
    o = conn.table_pandas("orders", ["o_orderkey", "o_orderdate"])
    li = conn.table_pandas("lineitem", ["l_orderkey", "l_quantity"])
    j = li.merge(
        o[o.o_orderdate < np.datetime64("1993-01-01")],
        left_on="l_orderkey", right_on="o_orderkey",
    )[["o_orderkey", "l_quantity"]].sort_values(
        ["o_orderkey", "l_quantity"]
    ).head(50).reset_index(drop=True)
    np.testing.assert_array_equal(
        got["o_orderkey"].to_numpy(), j["o_orderkey"].to_numpy()
    )
    np.testing.assert_allclose(
        got["l_quantity"].to_numpy(), j["l_quantity"].to_numpy()
    )
