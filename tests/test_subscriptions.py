"""Continuous-query subscriptions (presto_tpu/stream, ISSUE-17): the
serving layer's fresh-data tier.

The contract under test:

- A subscription re-executes its prepared template on version-epoch
  advance (streaming appends) and/or interval ticks; every delivered
  result reflects AT LEAST the epoch snapshot taken when its refresh
  fired (the freshness contract, asserted via ``wait_for_epoch``).
- N same-template subscriptions woken by one append meet at the
  ``TemplateBatchGate`` and stack into one vmapped dispatch.
- ``mode="approx"`` rides the sketch-join / sampled-scan machinery and
  arrives flagged ``approximate`` — never silently.
- The HTTP surface (subscribe / poll / cancel) and graceful drain
  behave like the rest of the serving layer.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest

from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runtime.errors import UserError
from presto_tpu.runtime.lifecycle import QueryManager
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session
from presto_tpu.server.frontend import HttpFrontend, QueryServer
from presto_tpu.stream import StreamWriter

WAIT_S = 60.0


def counter(name: str) -> float:
    return REGISTRY.snapshot().get(name, 0.0)


def make_server(**kwargs):
    conn = MemoryConnector()
    s = Session({"memory": conn},
                properties={"batched_dispatch": True,
                            "health_monitor": False})
    return conn, s, QueryServer(session=s, **kwargs)


def ticks(n, lo=0):
    return pd.DataFrame({
        "k": np.arange(lo, lo + n, dtype=np.int64),
        "v": (np.arange(lo, lo + n, dtype=np.int64) * 3) % 100,
    })


# ---------------------------------------------------------------------------
# refresh semantics + the freshness contract
# ---------------------------------------------------------------------------


def test_initial_then_epoch_refresh_is_fresh():
    _conn, s, server = make_server()
    w = StreamWriter(s)
    w.append("ticks", ticks(10))
    sub = server.subscribe("select count(*) c, sum(v) s from ticks", "t0")
    try:
        first = sub.wait_for_seq(1, timeout_s=WAIT_S)
        assert first.trigger == "initial"
        assert int(first.df["c"][0]) == 10
        assert first.epochs == {"ticks": 1}

        r = w.append("ticks", ticks(5, lo=10))
        got = sub.wait_for_epoch("ticks", r.epoch, timeout_s=WAIT_S)
        # the freshness contract: a result delivered for epoch>=2 must
        # include the epoch-2 rows — never a stale pre-append frame
        assert got.trigger == "epoch"
        assert int(got.df["c"][0]) == r.total_rows
        assert got.epochs["ticks"] >= r.epoch
        assert counter("subscription.stale_blocked") == 0
    finally:
        server.shutdown()


def test_every_delivered_result_meets_its_epoch_floor():
    """Appends racing refreshes: each delivered count must be >= the
    row count at its fire-time epoch (rows only ever grow)."""
    _conn, s, server = make_server()
    w = StreamWriter(s)
    rows_at_epoch = {}
    r = w.append("ticks", ticks(20))
    rows_at_epoch[r.epoch] = r.total_rows
    sub = server.subscribe("select count(*) c from ticks", "t0")
    try:
        for i in range(5):
            r = w.append("ticks", ticks(7, lo=100 * (i + 1)))
            rows_at_epoch[r.epoch] = r.total_rows
        sub.wait_for_epoch("ticks", r.epoch, timeout_s=WAIT_S)
        for res in sub.results():
            floor = rows_at_epoch.get(res.epochs.get("ticks"))
            if floor is not None:
                assert int(res.df["c"][0]) >= floor, (
                    f"stale: {res.df['c'][0]} rows delivered for epoch "
                    f"{res.epochs['ticks']} (floor {floor})")
        assert counter("subscription.stale_blocked") == 0
    finally:
        server.shutdown()


def test_interval_tick_refresh_without_writes():
    _conn, s, server = make_server()
    StreamWriter(s).append("ticks", ticks(4))
    sub = server.subscribe("select max(v) m from ticks", "t0",
                           interval_s=0.1)
    try:
        got = sub.wait_for_seq(3, timeout_s=WAIT_S)
        assert got.seq >= 3
        assert any(r.trigger == "interval" for r in sub.results())
    finally:
        server.shutdown()


def test_subscription_failure_paths_are_loud():
    _conn, s, server = make_server()
    StreamWriter(s).append("ticks", ticks(4))
    with pytest.raises(UserError, match="exact|approx"):
        server.subscribe("select 1", "t0", mode="wat")
    with pytest.raises(UserError, match="positive"):
        server.subscribe("select 1", "t0", interval_s=-1)
    with pytest.raises(UserError, match="placeholder"):
        server.subscribe("select count(*) from ticks where v < ?", "t0")
    sub = server.subscribe("select count(*) c from ticks", "t0")
    try:
        sub.wait_for_seq(1, timeout_s=WAIT_S)
        with pytest.raises(UserError, match="unknown subscription"):
            server.unsubscribe("sub_999")
    finally:
        server.shutdown()
    # shutdown cancelled it; waiting now raises typed, never hangs
    assert sub.state == "CANCELLED"
    with pytest.raises(UserError):
        sub.wait_for_seq(99, timeout_s=0.2)


def test_unsubscribe_deallocates_prepared_template():
    _conn, s, server = make_server()
    StreamWriter(s).append("ticks", ticks(4))
    sub = server.subscribe("select count(*) c from ticks", "t0")
    try:
        sub.wait_for_seq(1, timeout_s=WAIT_S)
        key = f"t0::{sub.id}"
        assert key in s._prepared
        server.unsubscribe(sub.id)
        assert key not in s._prepared
        assert sub.state == "CANCELLED"
    finally:
        server.shutdown()


def test_drain_blocks_new_subscriptions():
    _conn, s, server = make_server()
    StreamWriter(s).append("ticks", ticks(4))
    sub = server.subscribe("select count(*) c from ticks", "t0")
    sub.wait_for_seq(1, timeout_s=WAIT_S)
    server.shutdown()
    assert sub.state == "CANCELLED"
    with pytest.raises(UserError, match="draining"):
        server.subscribe("select count(*) c from ticks", "t0")


# ---------------------------------------------------------------------------
# same-template batching through the gate
# ---------------------------------------------------------------------------


def test_same_template_subscriptions_batch_through_gate(monkeypatch):
    """N dashboards on one template, different literals: one append
    wakes all of them, their concurrent refreshes meet at the
    TemplateBatchGate, and the gate fuses them into one vmapped
    dispatch (deterministically: the first leader is held until the
    followers queue, the test_server idiom)."""
    _conn, s, server = make_server()
    w = StreamWriter(s)
    w.append("ticks", ticks(50))
    # the dashboard shape: scan+filter+TopN auto-parameterizes its
    # literal (aggregate-only shapes do not — they ride the serial
    # template slot instead of the vmapped batch)
    fmt = "select k, v from ticks where v < {} order by k limit 100"
    lits = (25, 50, 75, 101)
    subs = [server.subscribe(fmt.format(lit), f"tenant-{i}")
            for i, lit in enumerate(lits)]
    assert all(s._prepared[f"tenant-{i}::{sub.id}"].auto_slots
               for i, sub in enumerate(subs)), (
        "template literals did not parameterize; the gate can never fuse")
    try:
        for sub in subs:
            sub.wait_for_seq(1, timeout_s=WAIT_S)  # initial fires drain

        gate = s.query_manager.batch_gate
        release = threading.Event()
        first = threading.Event()
        orig = QueryManager.run_plan

        def gated(self, executor, plan, info, recorder):
            if not first.is_set():
                first.set()
                release.wait(WAIT_S)
            return orig(self, executor, plan, info, recorder)

        monkeypatch.setattr(QueryManager, "run_plan", gated)
        d0 = counter("batch.dispatched")
        q0 = counter("batch.queries")
        r = w.append("ticks", ticks(50, lo=50))
        assert first.wait(WAIT_S)
        deadline = time.monotonic() + WAIT_S
        while time.monotonic() < deadline:
            depth = sum(gate.queue_depth(fp) for fp in list(gate._templates))
            if depth >= len(subs) - 1:
                break
            time.sleep(0.01)
        release.set()
        got = [sub.wait_for_epoch("ticks", r.epoch, timeout_s=WAIT_S)
               for sub in subs]
        dd = counter("batch.dispatched") - d0
        qd = counter("batch.queries") - q0
        assert dd >= 1, "subscription refreshes never fused at the gate"
        assert qd / dd > 1.0, f"mean batch size {qd}/{dd} <= 1"
        assert sum(res.batched for res in got) >= 2, "results not flagged"
        # fused or not, every dashboard sees the fresh (post-append) rows
        full = ticks(100)
        for res, lit in zip(got, lits):
            want = full[full["v"] < lit].sort_values("k").head(100)
            assert len(res.df) == len(want), (lit, len(res.df), len(want))
            assert res.df["k"].tolist() == want["k"].tolist()
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# the approximate tier
# ---------------------------------------------------------------------------


def _wide_domain_tables(w: StreamWriter, seed=7, n=4000, nkeys=500):
    """Semi-join shape whose build keys span ~1e12: the exact
    exists-bitmap can't admit the domain, so ``approx_join`` routes
    the probe through the Bloom sketch."""
    rng = np.random.default_rng(seed)
    ckeys = rng.integers(0, 1_000_000_000_000, nkeys).astype(np.int64)
    w.append("orders", pd.DataFrame({
        "okey": np.arange(n, dtype=np.int64),
        "ckey": np.concatenate([
            rng.choice(ckeys, n - 1000),
            rng.integers(0, 1_000_000_000_000, 1000),
        ]).astype(np.int64),
    }))
    w.append("cust", pd.DataFrame({
        "ckey": ckeys,
        "grp": rng.integers(0, 5, nkeys).astype(np.int64),
    }))
    return ("select count(*) n from orders where ckey in "
            "(select ckey from cust where grp = 3)")


def test_approx_subscription_sketch_join_superset_flagged():
    """ISSUE-17 acceptance: an approx-mode subscription's semi join
    rides the Bloom sketch — its result is a superset of exact (false
    positives only, never dropped rows) and arrives flagged
    ``approximate``."""
    # no budget override needed: the wide key domain alone disqualifies
    # the exact exists-bitmap (a tiny join_build_budget_bytes would
    # instead re-route the join through the grouped-spill tier, away
    # from the kernel entirely)
    _conn, s, server = make_server()
    sql = _wide_domain_tables(StreamWriter(s))
    exact = int(server.execute(sql, "t0")["n"][0])
    sub = server.subscribe(sql, "t0", mode="approx")
    try:
        got = sub.wait_for_seq(1, timeout_s=WAIT_S)
        assert got.approximate, "sketch-join refresh not flagged"
        assert int(got.df["n"][0]) >= exact, "approx dropped rows"
    finally:
        server.shutdown()
    # the exact ad-hoc run through the same server stayed unflagged
    infos = [i for i in s.query_history if i.tenant == "t0"]
    assert infos and not infos[0].approximate


def test_approx_subscription_sampled_scan_flagged():
    """``approx_scan_fraction`` < 1 in the approx tier: refreshes scan
    a strided subset of splits and are flagged approximate."""
    conn = MemoryConnector(units_per_split=64)
    s = Session({"memory": conn},
                properties={"batched_dispatch": True,
                            "health_monitor": False})
    server = QueryServer(session=s,
                         approx_properties={"approx_scan_fraction": 0.25})
    w = StreamWriter(s)
    w.append("ticks", ticks(1000))
    sub = server.subscribe("select count(*) c from ticks", "t0",
                           mode="approx")
    try:
        got = sub.wait_for_seq(1, timeout_s=WAIT_S)
        assert got.approximate, "sampled-scan refresh not flagged"
        n = int(got.df["c"][0])
        assert 0 < n < 1000, f"sampling did not drop splits (n={n})"
        exact = int(server.execute(
            "select count(*) c from ticks", "t0")["c"][0])
        assert exact == 1000, "exact tier must not sample"
    finally:
        server.shutdown()


def test_exact_and_approx_subscriptions_never_share_cache():
    """Fingerprints fold the approx knobs: the same SQL subscribed in
    both modes never serves one tier's frame to the other."""
    conn = MemoryConnector(units_per_split=64)
    s = Session({"memory": conn},
                properties={"batched_dispatch": True,
                            "health_monitor": False})
    server = QueryServer(session=s,
                         approx_properties={"approx_scan_fraction": 0.25})
    w = StreamWriter(s)
    w.append("ticks", ticks(1000))
    sql = "select count(*) c from ticks"
    exact_sub = server.subscribe(sql, "t0")
    approx_sub = server.subscribe(sql, "t0", mode="approx")
    try:
        e = exact_sub.wait_for_seq(1, timeout_s=WAIT_S)
        a = approx_sub.wait_for_seq(1, timeout_s=WAIT_S)
        assert int(e.df["c"][0]) == 1000 and not e.approximate
        assert int(a.df["c"][0]) < 1000 and a.approximate
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


def test_http_subscribe_poll_cancel_round_trip():
    _conn, s, server = make_server()
    w = StreamWriter(s)
    w.append("ticks", ticks(10))
    fe = HttpFrontend(server, port=0).start_background()
    base = f"http://127.0.0.1:{fe.port}"

    def post(path, body):
        req = urllib.request.Request(
            base + path, method="POST", data=json.dumps(body).encode(),
            headers={"X-Presto-Tenant": "dash"})
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def get(path):
        with urllib.request.urlopen(base + path) as r:
            return r.status, json.loads(r.read())

    try:
        st, body = post("/v1/subscribe",
                        {"sql": "select count(*) c from ticks"})
        assert st == 201 and body["tables"] == ["ticks"]
        sid, uri = body["id"], body["nextUri"]

        deadline = time.monotonic() + WAIT_S
        page = {}
        while time.monotonic() < deadline:
            _, page = get(uri)
            if page.get("seq", 0) >= 1:
                break
            time.sleep(0.02)
        assert page["data"] == [[10]] and page["tenant"] == "dash"

        r = w.append("ticks", ticks(3, lo=10))
        deadline = time.monotonic() + WAIT_S
        while time.monotonic() < deadline:
            _, page = get(uri)
            if page.get("epochs", {}).get("ticks", 0) >= r.epoch:
                break
            time.sleep(0.02)
        assert page["data"] == [[13]], "poll page served a stale frame"

        st, body = post(f"/v1/subscription/{sid}/cancel", {})
        assert st == 200 and body == {"cancelled": sid}
        st, body = post("/v1/subscribe", {"notsql": 1})
        assert st == 400
        st, body = post("/v1/subscribe", {"sql": "select 1", "mode": "wat"})
        assert st == 400
    finally:
        fe.shutdown()
        server.shutdown()
