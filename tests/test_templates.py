"""Plan-template parameterization (plan/templates.py, ISSUE-10).

Reference parity: prepared statements (``PREPARE`` / ``EXECUTE ...
USING``) whose plans are cached by template [SURVEY §2.1]. The
contract under test, position class by position class:

- ELIGIBLE literal positions (projection arithmetic, filter bounds
  outside leaf fragments, join keys via projections, agg inputs) slot
  into ``expr.Param`` — warm same-template/different-literal queries
  re-trace ZERO jitted steps (the ``exec.traces`` probe) and results
  are bit-identical to ``plan_templates=0``.
- INELIGIBLE positions (leaf-route spec bounds, LIMIT shapes) stay
  baked with loud ``prepare.slot_ineligible.*`` counters — distinct
  bindings are distinct templates, still bit-identical on/off.
- Concurrent identical queries coalesce onto ONE dispatch; concurrent
  same-template different-literal queries ride one warm executable.
- The result cache keys on the FULL binding: compile work is shared
  across literals, results never are.
"""

import threading
import time

import pandas as pd
import pytest

from presto_tpu.cache.exec_cache import trace_delta
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.errors import UserError
from presto_tpu.runtime.lifecycle import InflightCoalescer, QueryManager
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.runtime.session import Session

CONN = TpchConnector(sf=0.01)


def make_session(**props):
    props.setdefault("result_cache_enabled", False)
    return Session({"tpch": CONN}, properties=props)


def counter(name: str) -> float:
    return REGISTRY.snapshot().get(name, 0.0)


#: one template per eligible position class: (name, format string,
#: literal sweep). None of these fragments is leaf-route shaped (a
#: joined build output / bare projection breaks the matcher), so every
#: literal here must slot.
ELIGIBLE_POSITIONS = [
    ("projection_arith",
     "select l_orderkey, l_linenumber, l_extendedprice + {} p from lineitem"
     " order by l_orderkey, l_linenumber limit 20",
     (5, 250, 4000)),
    ("filter_bound",
     "select l_orderkey, l_linenumber, l_quantity from lineitem"
     " where l_extendedprice < {}"
     " order by l_orderkey, l_linenumber limit 30",
     (2000, 20000, 90000)),
    ("join_filter_bound",
     "select o_orderpriority, count(*) c from lineitem"
     " join orders on l_orderkey = o_orderkey where l_quantity < {}"
     " group by o_orderpriority order by o_orderpriority",
     (10, 24, 44)),
    ("join_key_via_projection",
     "select o_orderpriority, count(*) c from"
     " (select l_orderkey + {} k from lineitem) l"
     " join orders on k = o_orderkey"
     " group by o_orderpriority order by o_orderpriority",
     (0, 3, 11)),
    ("agg_input",
     "select o_orderpriority, sum(l_quantity + {}) s from lineitem"
     " join orders on l_orderkey = o_orderkey"
     " group by o_orderpriority order by o_orderpriority",
     (0, 7, 29)),
]


# ---------------------------------------------------------------------------
# eligible positions: zero warm re-traces + on/off differential
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "name,fmt,lits", ELIGIBLE_POSITIONS, ids=[p[0] for p in ELIGIBLE_POSITIONS]
)
def test_eligible_position_zero_warm_retraces(name, fmt, lits):
    s = make_session()
    dfs = {lits[0]: s.sql(fmt.format(lits[0]))}  # cold: trace once
    # warm bindings all inside ONE trace_delta window (exec.traces is
    # process-global — keep the off-session's runs OUTSIDE the window,
    # or their traces would fake a failure: the PR 9 footgun the
    # helper exists to retire)
    with trace_delta() as td:
        for v in lits[1:]:
            dfs[v] = s.sql(fmt.format(v))
            assert s.query_history[-1].template_hit
    assert td.traces == 0, \
        f"{name}: warm same-template bindings re-traced"
    off = make_session(plan_templates=False)
    for v, df in dfs.items():
        pd.testing.assert_frame_equal(df, off.sql(fmt.format(v)))


def test_off_mode_retraces_fresh_literals():
    """Meaningfulness check for the sweep above: with templates OFF the
    same fresh-literal stream really does re-trace (otherwise a zero
    delta would prove nothing)."""
    _, fmt, _lits = ELIGIBLE_POSITIONS[1]
    off = make_session(plan_templates=False)
    # literals no other test in this PROCESS has baked: the exec cache
    # is process-global and content-keyed, so a reused literal would be
    # legitimately warm even with templates off
    off.sql(fmt.format(3333))
    with trace_delta() as td:
        off.sql(fmt.format(7777))
    assert td.traces > 0
    assert not off.query_history[-1].template_hit


# ---------------------------------------------------------------------------
# ineligible positions: baked, counted, still correct
# ---------------------------------------------------------------------------


def test_leaf_route_literals_stay_baked():
    """A Q6-shaped fragment lowers through the fused leaf-kernel family
    whose spec PROOFS (rescaled closed bounds, int32 hulls) consume the
    filter literal — slotting it would change kernel admission per
    binding. It stays baked: distinct literals are distinct templates,
    loudly counted, results still identical on/off."""
    fmt = ("select sum(l_extendedprice * l_discount) rev from lineitem"
           " where l_quantity < {}")
    s = make_session()
    i0 = counter("prepare.slot_ineligible.leaf_route")
    df1 = s.sql(fmt.format(30))
    assert counter("prepare.slot_ineligible.leaf_route") > i0
    s.sql(fmt.format(30))
    assert s.query_history[-1].template_hit  # same literal: same template
    df2 = s.sql(fmt.format(17))
    assert not s.query_history[-1].template_hit  # baked: new template
    off = make_session(plan_templates=False)
    pd.testing.assert_frame_equal(df1, off.sql(fmt.format(30)))
    pd.testing.assert_frame_equal(df2, off.sql(fmt.format(17)))


def test_limit_stays_baked():
    """LIMIT / TopN counts are static output *shapes*, never slots."""
    fmt = "select l_orderkey from lineitem order by l_orderkey limit {}"
    s = make_session()
    i0 = counter("prepare.slot_ineligible.limit")
    df1 = s.sql(fmt.format(10))
    assert counter("prepare.slot_ineligible.limit") > i0
    df2 = s.sql(fmt.format(25))
    assert not s.query_history[-1].template_hit  # new shape, new template
    assert len(df1) == 10 and len(df2) == 25
    off = make_session(plan_templates=False)
    pd.testing.assert_frame_equal(df2, off.sql(fmt.format(25)))


# ---------------------------------------------------------------------------
# PREPARE / EXECUTE surface
# ---------------------------------------------------------------------------


def test_prepare_execute_python_api():
    s = make_session()
    h = s.prepare("select count(*) c from orders where o_orderkey < ?")
    df1, info1 = s.execute(h, [512])
    with trace_delta() as td:
        df2, info2 = s.execute(h, [4096])
    assert td.traces == 0  # new binding, zero re-traces
    assert info2.template_hit and info2.state == "FINISHED"
    off = make_session(plan_templates=False)
    pd.testing.assert_frame_equal(
        df1, off.sql("select count(*) c from orders where o_orderkey < 512"))
    pd.testing.assert_frame_equal(
        df2, off.sql("select count(*) c from orders where o_orderkey < 4096"))


def test_prepare_execute_sql_surface():
    s = make_session()
    out = s.sql("prepare p_rng from select count(*) c from orders"
                " where o_orderkey between ? and ?")
    assert out["prepared"].tolist() == ["p_rng"]
    a = s.sql("execute p_rng using 100, 2000")
    off = make_session(plan_templates=False)
    pd.testing.assert_frame_equal(
        a, off.sql("select count(*) c from orders"
                   " where o_orderkey between 100 and 2000"))
    # negative literals parse through the unary-minus fold
    b = s.sql("execute p_rng using -5, 900")
    pd.testing.assert_frame_equal(
        b, off.sql("select count(*) c from orders"
                   " where o_orderkey between -5 and 900"))
    s.sql("deallocate prepare p_rng")
    with pytest.raises(UserError, match="not found"):
        s.sql("execute p_rng using 1, 2")
    with pytest.raises(UserError, match="not found"):
        s.sql("deallocate prepare p_rng")


def test_execute_binding_errors():
    s = make_session()
    h = s.prepare("select count(*) c from orders where o_orderkey < ?")
    with pytest.raises(UserError, match="takes 1 parameter"):
        s.execute(h, [])
    with pytest.raises(UserError, match="takes 1 parameter"):
        s.execute(h, [1, 2])
    with pytest.raises(UserError, match="cannot bind"):
        s.execute(h, ["not-a-number"])
    with pytest.raises(UserError, match="cannot bind"):
        s.execute(h, [1.5])  # non-integral value for an integer slot


def test_param_typing_errors():
    s = make_session()
    # a ? with no typed context cannot be typed
    with pytest.raises(UserError, match="cannot infer"):
        s.prepare("select ? x from region")
    # both comparison sides untyped
    with pytest.raises(UserError, match="cannot infer"):
        s.prepare("select count(*) c from region where ? = ?")
    # string parameters are trace-time dictionary work, not device
    # scalars — rejected at prepare, not silently baked
    with pytest.raises(UserError, match="string parameters"):
        s.prepare("select count(*) c from region where r_name = ?")
    # raw sql()/plan()/execute() with placeholders have no values to
    # bind — all reject at PLAN time (never a KeyError mid-trace)
    with pytest.raises(UserError, match="PREPARE"):
        s.sql("select count(*) c from orders where o_orderkey < ?")
    with pytest.raises(UserError, match="PREPARE"):
        s.plan("select count(*) c from orders where o_orderkey < ?")
    with pytest.raises(UserError, match="PREPARE"):
        s.execute("select count(*) c from orders where o_orderkey < ?")


def test_in_list_params():
    s = make_session()
    h = s.prepare("select count(*) c from orders"
                  " where o_orderkey in (?, 7, ?)")
    df, _ = s.execute(h, [1, 32])
    off = make_session(plan_templates=False)
    pd.testing.assert_frame_equal(
        df, off.sql("select count(*) c from orders"
                    " where o_orderkey in (1, 7, 32)"))


# ---------------------------------------------------------------------------
# binding identity: results are never shared across literals
# ---------------------------------------------------------------------------


def test_result_cache_keys_on_full_binding():
    s = Session({"tpch": CONN})  # result cache ON
    fmt = ("select l_orderkey, l_linenumber, l_quantity from lineitem"
           " where l_extendedprice < {}"
           " order by l_orderkey, l_linenumber limit 30")
    df1 = s.sql(fmt.format(2000))
    df2 = s.sql(fmt.format(90000))  # same template, different binding
    assert not s.query_history[-1].cache_hit  # results are per-binding
    assert not df1.equals(df2)  # different bindings, different rows
    h0 = counter("result_cache.hit")
    df1b = s.sql(fmt.format(2000))
    assert counter("result_cache.hit") == h0 + 1
    pd.testing.assert_frame_equal(df1, df1b)


def test_explain_renders_param_slots():
    s = make_session()
    out = s.explain("select l_orderkey, l_extendedprice + 7 p from lineitem"
                    " where l_extendedprice < 2000"
                    " order by l_orderkey limit 5")
    assert "params=[" in out and "?0=" in out and "?1=" in out
    off = make_session(plan_templates=False)
    out_off = off.explain(
        "select l_orderkey, l_extendedprice + 7 p from lineitem"
        " where l_extendedprice < 2000"
        " order by l_orderkey limit 5")
    assert "params=[" not in out_off and "?0" not in out_off


def test_query_history_template_hit_column():
    s = make_session()
    q = ("select o_orderpriority, count(*) c from orders"
         " group by o_orderpriority order by o_orderpriority")
    s.sql(q)
    s.sql(q)
    df = s.sql("select template_hit, coalesced from query_history")
    assert df["template_hit"].max() == 1
    assert set(df["coalesced"].tolist()) <= {0, 1}


# ---------------------------------------------------------------------------
# in-flight coalescing
# ---------------------------------------------------------------------------


def test_concurrent_identical_queries_coalesce(monkeypatch):
    """N concurrent submissions of one identical query = ONE device
    dispatch + N correct results. The leader is gated inside run_plan
    until every follower has registered, so the coalesce is
    deterministic, not a timing accident. The registration gate itself
    is re-attempted (a worker thread can be scheduled arbitrarily late
    on a loaded 1-core box — then a second dispatch is CORRECT
    opportunistic behavior, not a coalescing bug); worker exceptions
    are captured and surfaced, never swallowed into a thread death."""
    s = make_session()
    q = ("select o_orderpriority, count(*) c from orders"
         " group by o_orderpriority order by o_orderpriority")
    expected = s.sql(q)  # warm compile; also the correctness oracle
    coal = s.query_manager.coalescer
    orig = QueryManager.run_plan

    for attempt in range(3):
        release = threading.Event()
        calls = []

        def gated(self, executor, plan, info, recorder,
                  _release=release, _calls=calls):
            _calls.append(info.query_id)
            _release.wait(20)
            return orig(self, executor, plan, info, recorder)

        monkeypatch.setattr(QueryManager, "run_plan", gated)
        results, errors = {}, []

        def worker(i, _results, _errors):
            try:
                _results[i] = s.sql(q)
            except Exception as e:  # noqa: BLE001 — surfaced below
                _errors.append((i, repr(e)))

        c0 = counter("prepare.coalesced")
        threads = [threading.Thread(target=worker,
                                    args=(i, results, errors))
                   for i in range(4)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 20
        registered = False
        while time.monotonic() < deadline:
            with coal._lock:
                waiting = sum(e.waiters
                              for e in coal._inflight.values())
            if calls and waiting == 3:
                registered = True
                break
            time.sleep(0.01)
        release.set()
        for t in threads:
            t.join(60)
        monkeypatch.setattr(QueryManager, "run_plan", orig)
        assert not errors, f"worker exceptions (attempt {attempt}): " \
                           f"{errors}"
        if registered:
            break
    else:
        pytest.fail("followers never all registered in 3 attempts "
                    f"(last: calls={calls})")

    assert len(calls) == 1, f"expected one dispatch, saw {len(calls)}"
    assert counter("prepare.coalesced") == c0 + 3
    for df in results.values():
        pd.testing.assert_frame_equal(df, expected)
    assert sum(i.coalesced for i in s.query_history) >= 3


def test_concurrent_distinct_literals_ride_one_warm_template():
    """Same template, different literals, submitted concurrently: the
    template slot serializes them behind ONE warm executable — zero
    re-traces across the whole burst."""
    s = make_session()
    fmt = ("select l_orderkey, l_linenumber, l_quantity from lineitem"
           " where l_extendedprice < {}"
           " order by l_orderkey, l_linenumber limit 30")
    s.sql(fmt.format(1000))  # compile the template once
    lits = (2000, 20000, 50000, 90000)
    results = {}

    def worker(v):
        results[v] = s.sql(fmt.format(v))

    with trace_delta() as td:
        threads = [threading.Thread(target=worker, args=(v,)) for v in lits]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
    assert td.traces == 0, "concurrent bindings re-traced"
    off = make_session(plan_templates=False)
    for v in lits:
        pd.testing.assert_frame_equal(results[v], off.sql(fmt.format(v)))


def test_coalescer_failed_leader_releases_followers():
    """Followers of a failed leader get None and execute themselves:
    coalescing batches work, never failures."""
    coal = InflightCoalescer()
    lead, entry = coal.lead_or_wait("k")
    assert lead
    out = []
    th = threading.Thread(
        target=lambda: out.append(coal.lead_or_wait("k", 10)))
    th.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and coal.waiters("k") == 0:
        time.sleep(0.005)
    coal.publish("k", entry, None)  # the leader failed
    th.join(10)
    assert out == [(False, None)]
    # the key was retired at publish: a late arrival leads fresh
    lead2, entry2 = coal.lead_or_wait("k")
    assert lead2
    coal.publish("k", entry2, None)


def test_failed_executor_setup_retires_inflight_entry(monkeypatch):
    """A failure BETWEEN coalescer registration and the publishing
    try/finally (e.g. executor construction) must retire the in-flight
    key — otherwise every later identical query blocks the full
    coalesce wait on an entry nobody will publish."""
    s = make_session(query_retries=0)
    q = ("select o_orderpriority, count(*) c from orders"
         " group by o_orderpriority order by o_orderpriority")
    expected = s.sql(q)
    orig = Session._make_executor

    def boom(self):
        raise RuntimeError("executor setup failed")

    monkeypatch.setattr(Session, "_make_executor", boom)
    with pytest.raises(RuntimeError):
        s.sql(q)
    monkeypatch.setattr(Session, "_make_executor", orig)
    t0 = time.monotonic()
    pd.testing.assert_frame_equal(s.sql(q), expected)
    # promptly, not after a dead-entry coalesce timeout
    assert time.monotonic() - t0 < 10


def test_coalescer_serves_defensive_copies():
    coal = InflightCoalescer()
    lead, entry = coal.lead_or_wait("k")
    src = pd.DataFrame({"x": [1, 2, 3]})
    got = []

    def follow():
        got.append(coal.lead_or_wait("k", 10))

    threads = [threading.Thread(target=follow) for _ in range(2)]
    for th in threads:
        th.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and coal.waiters("k") < 2:
        time.sleep(0.005)
    coal.publish("k", entry, src)
    for th in threads:
        th.join(10)
    (_, df1), (_, df2) = got
    df1.loc[:, "x"] = -1
    # neither the leader's frame nor the sibling follower's is aliased
    assert src["x"].tolist() == [1, 2, 3]
    assert df2["x"].tolist() == [1, 2, 3]


# ---------------------------------------------------------------------------
# distributed executor
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_distributed_template_zero_warm_retraces():
    """The slot-value vector threads through the shard_map steps too:
    warm bindings re-trace zero jitted steps on the distributed tier,
    and results match the local on/off runs."""
    from presto_tpu.parallel.mesh import make_mesh

    s = Session({"tpch": CONN}, mesh=make_mesh(8),
                properties={"result_cache_enabled": False})
    fmt = ("select o_orderpriority, count(*) c, sum(l_quantity + {}) s"
           " from lineitem join orders on l_orderkey = o_orderkey"
           " where l_extendedprice < {}"
           " group by o_orderpriority order by o_orderpriority")
    dfs = {(0, 20000): s.sql(fmt.format(0, 20000))}
    with trace_delta() as td:
        for args in ((7, 50000), (29, 90000)):
            dfs[args] = s.sql(fmt.format(*args))
            assert s.query_history[-1].template_hit
    assert td.traces == 0, "distributed warm bindings re-traced"
    off = make_session(plan_templates=False)
    for args, df in dfs.items():
        pd.testing.assert_frame_equal(df, off.sql(fmt.format(*args)))
