"""Engine-vs-oracle differential tests for the TPC-DS query subset
(reference parity: presto-tpcds query tests + H2QueryRunner diffing
[SURVEY §4]). Also exercises NULL FK semantics: fact tables carry ~4%
NULL date/promo/cdemo keys that inner joins must drop."""

import numpy as np
import pandas as pd
import pytest

pytestmark = pytest.mark.slow

from presto_tpu.connectors.tpcds import TpcdsConnector
from presto_tpu.connectors.tpcds.queries import QUERIES
from presto_tpu.oracle.tpcds_oracle import ORACLES
from presto_tpu.runtime.session import Session

from tests.test_tpch_sql import compare

SF = 0.02


@pytest.fixture(scope="module")
def env():
    conn = TpcdsConnector(sf=SF, units_per_split=1 << 15)
    session = Session({"tpcds": conn})
    tables = {name: conn.table_pandas(name) for name in conn.tables()}
    return session, tables


def test_generator_determinism():
    # same config -> identical data (streams are (table, chunk, column)
    # keyed, so any column/chunk subset regenerates identically)
    a = TpcdsConnector(sf=0.01).table_numpy("store_sales", ["ss_item_sk"])
    b = TpcdsConnector(sf=0.01).table_numpy("store_sales", ["ss_item_sk"])
    np.testing.assert_array_equal(a["ss_item_sk"], b["ss_item_sk"])
    # column pruning never perturbs other columns
    conn = TpcdsConnector(sf=0.01)
    s = conn.splits("store_sales")[0]
    full = conn.scan_numpy(s)
    pruned = conn.scan_numpy(s, ["ss_item_sk", "ss_net_paid"])
    np.testing.assert_array_equal(full["ss_item_sk"], pruned["ss_item_sk"])
    np.testing.assert_array_equal(full["ss_net_paid"], pruned["ss_net_paid"])


def test_fact_nulls_flow_through(env):
    session, tables = env
    got = session.sql("select count(*) as n, count(ss_sold_date_sk) as nd "
                      "from store_sales")
    ss = tables["store_sales"]
    assert int(got["n"][0]) == len(ss)
    assert int(got["nd"][0]) == int(ss["ss_sold_date_sk"].notna().sum())
    assert int(got["nd"][0]) < int(got["n"][0])  # NULLs actually present


_HUGE = {"q14", "q23", "q24", "q54", "q64"}  # ~10-min fixtures each


@pytest.mark.parametrize(
    "name",
    [pytest.param(n, marks=pytest.mark.huge) if n in _HUGE
     else n
     for n in sorted(QUERIES, key=lambda x: int(x[1:]))],
)
def test_tpcds_query_matches_oracle(env, name):
    session, tables = env
    got = session.sql(QUERIES[name])
    want = ORACLES[name](tables)
    assert len(want) > 0, f"{name}: oracle returned no rows (bad constants)"
    compare(got, want, name)


@pytest.mark.parametrize(
    "name", ["q3", "q7", "q98", "q33", "q36", "q38", "q97", "q10",
             "q16", "q76", "q22", "q28", "q47", "q95"]
)
def test_tpcds_distributed_matches_oracle(env, name):
    """Star joins, NULL-key joins, window-over-aggregate (q98),
    three-channel UNION ALL (q33), ROLLUP + grouping() + rank (q36),
    INTERSECT (q38), FULL OUTER JOIN (q97), OR-of-EXISTS mark joins
    (q10), correlated EXISTS/NOT-EXISTS on multi-line orders (q16),
    string-literal group keys over UNION ALL (q76), 4-level rollup with
    a wide free-text key (q22), scalar-subquery fan (q28), window
    offsets over grouped series (q47), and the q95 double-EXISTS CTE —
    through the real mesh exchanges (DistributedQueryRunner analog)."""
    from presto_tpu.parallel.mesh import make_mesh

    session, tables = env
    dist = Session({"tpcds": session.catalog.connector("tpcds")},
                   mesh=make_mesh(8))
    compare(dist.sql(QUERIES[name]), ORACLES[name](tables), f"dist_{name}")
