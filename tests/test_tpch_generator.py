"""TPC-H generator tests: determinism, referential integrity, spec
distributions (reference parity: airlift tpch generator tests [SURVEY §2.2])."""

import numpy as np
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch import schema as S
from presto_tpu.connectors.tpch.generator import (
    customer_draw_to_key,
    order_index_to_key,
    partsupp_suppkey,
)

SF = 0.01  # 1500 customers, 15000 orders, ~60000 lineitems


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=SF, units_per_split=4096)


def test_row_counts(conn):
    assert len(conn.table_numpy("customer")["c_custkey"]) == 1500
    assert len(conn.table_numpy("orders")["o_orderkey"]) == 15000
    assert len(conn.table_numpy("part")["p_partkey"]) == 2000
    assert len(conn.table_numpy("partsupp")["ps_partkey"]) == 8000
    assert len(conn.table_numpy("supplier")["s_suppkey"]) == 100
    n = len(conn.table_numpy("lineitem", ["l_orderkey"])["l_orderkey"])
    assert 15000 * 1 <= n <= 15000 * 7
    assert abs(n / 15000 - 4.0) < 0.1  # mean lines/order


def test_determinism_and_column_pruning_stability(conn):
    s = conn.splits("lineitem")[0]
    a = conn.scan_numpy(s, ["l_orderkey", "l_quantity", "l_comment"])
    b = conn.scan_numpy(s, ["l_quantity"])
    np.testing.assert_array_equal(a["l_quantity"], b["l_quantity"])
    c = conn.scan_numpy(s, ["l_comment"])
    np.testing.assert_array_equal(a["l_comment"], c["l_comment"])


def test_orderkey_sparsity():
    idx = np.arange(32)
    keys = order_index_to_key(idx)
    assert keys[0] == 1 and keys[7] == 8 and keys[8] == 33
    assert ((keys - 1) % 32 < 8).all()


def test_custkey_thirds():
    draws = np.arange(1000)
    keys = customer_draw_to_key(draws)
    assert (keys % 3 != 0).all()
    assert len(np.unique(keys)) == 1000


def test_partsupp_four_distinct_suppliers(conn):
    ps = conn.table_numpy("partsupp", ["ps_partkey", "ps_suppkey"])
    pairs = set(zip(ps["ps_partkey"].tolist(), ps["ps_suppkey"].tolist()))
    assert len(pairs) == len(ps["ps_partkey"])  # (partkey, suppkey) unique
    assert (ps["ps_suppkey"] >= 1).all() and (ps["ps_suppkey"] <= 100).all()


def test_lineitem_fk_into_partsupp(conn):
    """Every (l_partkey, l_suppkey) must exist in partsupp (Q9 join)."""
    li = conn.table_numpy("lineitem", ["l_partkey", "l_suppkey"])
    ps = conn.table_numpy("partsupp", ["ps_partkey", "ps_suppkey"])
    pairs = set(zip(ps["ps_partkey"].tolist(), ps["ps_suppkey"].tolist()))
    li_pairs = set(zip(li["l_partkey"].tolist(), li["l_suppkey"].tolist()))
    assert li_pairs <= pairs


def test_orders_fk_into_customer(conn):
    o = conn.table_numpy("orders", ["o_custkey"])
    assert (o["o_custkey"] >= 1).all() and (o["o_custkey"] <= 1500).all()
    assert (o["o_custkey"] % 3 != 0).all()


def test_date_relationships(conn):
    li = conn.table_numpy(
        "lineitem", ["l_shipdate", "l_commitdate", "l_receiptdate"]
    )
    o = conn.table_numpy("orders", ["o_orderdate"])
    assert (li["l_receiptdate"] > li["l_shipdate"]).all()
    assert (li["l_receiptdate"] - li["l_shipdate"] <= 30).all()
    assert (o["o_orderdate"] >= S.STARTDATE).all()
    assert (o["o_orderdate"] <= S.ORDER_MAXDATE).all()


def test_returnflag_linestatus_rule(conn):
    li = conn.table_numpy(
        "lineitem", ["l_returnflag", "l_linestatus", "l_shipdate", "l_receiptdate"]
    )
    dflag = S.DICTS["l_returnflag"]
    dstat = S.DICTS["l_linestatus"]
    n_code = dflag.code_of("N")
    late = li["l_receiptdate"] > S.CURRENTDATE
    assert ((li["l_returnflag"] == n_code) == late).all()
    open_ = li["l_shipdate"] > S.CURRENTDATE
    assert ((li["l_linestatus"] == dstat.code_of("O")) == open_).all()


def test_totalprice_matches_lineitems(conn):
    o = conn.table_numpy("orders", ["o_orderkey", "o_totalprice"])
    li = conn.table_numpy(
        "lineitem", ["l_orderkey", "l_extendedprice", "l_discount", "l_tax"]
    )
    charge = (
        li["l_extendedprice"] * (100 - li["l_discount"]) * (100 + li["l_tax"])
    )
    charge = (charge + 5000) // 10000
    import pandas as pd

    got = pd.Series(charge).groupby(li["l_orderkey"]).sum()
    want = pd.Series(o["o_totalprice"], index=o["o_orderkey"])
    joined = want.to_frame("want").join(got.rename("got"))
    assert (joined["want"] == joined["got"]).all()


def test_comment_injection_rates(conn):
    df = conn.table_pandas("orders", ["o_comment"])
    frac = df["o_comment"].str.contains(r"special.*requests").mean()
    assert 0.005 < frac < 0.10
    sup = conn.table_pandas("supplier", ["s_comment"])
    assert sup["s_comment"].str.contains("Customer").any() or len(sup) < 2000


def test_scan_to_batch(conn):
    s = conn.splits("lineitem")[0]
    b = conn.scan(s, ["l_orderkey", "l_quantity", "l_returnflag", "l_shipdate"])
    assert b.capacity >= s.row_hint / 7
    df = b.to_pandas()
    assert set(df["l_returnflag"]) <= {"R", "A", "N"}
    assert (df["l_quantity"] >= 1).all() and (df["l_quantity"] <= 50).all()


def test_nation_region(conn):
    n = conn.table_pandas("nation")
    r = conn.table_pandas("region")
    assert len(n) == 25 and len(r) == 5
    assert "GERMANY" in set(n["n_name"])
    assert set(n["n_regionkey"]) == {0, 1, 2, 3, 4}


def test_partsupp_pk_holds_at_tiny_sf():
    """Regression: S=50 (sf=0.005) used to produce duplicate
    (ps_partkey, ps_suppkey) pairs via a degenerate supplier step."""
    c = TpchConnector(sf=0.005)
    ps = c.table_numpy("partsupp", ["ps_partkey", "ps_suppkey"])
    pairs = list(zip(ps["ps_partkey"].tolist(), ps["ps_suppkey"].tolist()))
    assert len(set(pairs)) == len(pairs)
