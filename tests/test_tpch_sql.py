"""Engine-vs-oracle differential tests for all 22 TPC-H queries
(reference parity: AbstractTestQueries + H2QueryRunner diffing
MaterializedResults [SURVEY §4])."""

import numpy as np
import pandas as pd
import pytest

pytestmark = pytest.mark.slow

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.tpch.queries import QUERIES
from presto_tpu.oracle.tpch_oracle import ORACLES
from presto_tpu.runtime.session import Session

SF = 0.005


@pytest.fixture(scope="module")
def env():
    conn = TpchConnector(sf=SF, units_per_split=1 << 14)
    session = Session({"tpch": conn})
    tables = {name: conn.table_pandas(name) for name in conn.tables()}
    return session, tables


def normalize(df: pd.DataFrame) -> pd.DataFrame:
    df = df.copy()
    df.columns = [f"c{i}" for i in range(len(df.columns))]
    for c in df.columns:
        if pd.api.types.is_float_dtype(df[c]):
            df[c] = df[c].astype(np.float64).round(2)
        elif pd.api.types.is_datetime64_any_dtype(df[c]):
            df[c] = df[c].astype("datetime64[s]")
        elif df[c].dtype == object or pd.api.types.is_string_dtype(df[c]):
            # engine NULL doubles ride object columns as Python None
            # beside real floats (stddev of a 1-row sample, NULL lag
            # windows); astype(str) would freeze those None values into
            # the literal string 'None' and poison the float compare
            # below. A numeric-or-null object column aligns with the
            # oracle's NaN floats instead.
            vals = df[c].dropna()
            if len(vals) == 0 or vals.map(
                lambda v: isinstance(v, (int, float, np.number))
                and not isinstance(v, bool)
            ).all():
                df[c] = df[c].astype(np.float64).round(2)
            else:
                df[c] = df[c].astype(str).str.rstrip()
        else:
            df[c] = pd.to_numeric(df[c]).astype(np.int64)
    return df.sort_values(list(df.columns), kind="stable").reset_index(drop=True)


def compare(got: pd.DataFrame, want: pd.DataFrame, query: str):
    assert got.shape == want.shape, (
        f"{query}: shape {got.shape} != oracle {want.shape}"
    )
    if len(got) == 0:
        return
    g = normalize(got)
    w = normalize(want)
    for c in g.columns:
        if pd.api.types.is_float_dtype(w[c]):
            if not pd.api.types.is_float_dtype(g[c]):
                # engine NULL doubles surface as None (object column);
                # the oracle has NaN floats — align for allclose
                g[c] = g[c].astype(np.float64)
            np.testing.assert_allclose(
                g[c].to_numpy(), w[c].to_numpy(), rtol=1e-3, atol=0.02,
                err_msg=f"{query}: column {c}",
            )
        else:
            assert g[c].tolist() == w[c].tolist(), f"{query}: column {c}"


@pytest.mark.parametrize("name", sorted(QUERIES, key=lambda x: int(x[1:])))
def test_tpch_query_matches_oracle(env, name):
    session, tables = env
    got = session.sql(QUERIES[name])
    want = ORACLES[name](tables)
    compare(got, want, name)
