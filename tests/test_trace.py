"""Observability layer: span traces, histogram metrics, query history,
event ordering, and the <5% recording-overhead bound (ISSUE-3).

Reference parity targets: OperatorStats/QueryStats rollups, the
EventListener SPI, and tracing hooks [SURVEY §5.1, §5.5].
"""

import json
import threading
import time

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runtime.errors import UserError
from presto_tpu.runtime.metrics import REGISTRY, HistogramStat, MetricsRegistry
from presto_tpu.runtime.session import Session
from presto_tpu.runtime.stats import NodeIds, QueryInfo, StatsRecorder

Q_AGG = (
    "select l_returnflag, l_linestatus, count(*) c, sum(l_quantity) q "
    "from lineitem group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=0.005)


def _span_path_cats(rec, span):
    """Categories along a span's ancestor chain (incl. the span)."""
    by_id = {s.span_id: s for s in rec.spans}
    cats = []
    cur = span
    while cur is not None:
        cats.append(cur.cat)
        cur = by_id.get(cur.parent_id)
    return cats


# ---------------------------------------------------------------------------
# span recording + export
# ---------------------------------------------------------------------------


def test_local_query_records_nested_spans(conn):
    s = Session({"tpch": conn}, trace_token="tok-local")
    s.sql(Q_AGG)
    rec = s.traces.latest()
    assert rec is not None and rec.trace_token == "tok-local"
    roots = [sp for sp in rec.spans if sp.parent_id == -1]
    assert [sp.cat for sp in roots] == ["query"]
    steps = rec.spans_by_cat("step")
    assert steps, "no jitted-step spans recorded"
    # at least one step nests under node and query (the full chain)
    chains = [_span_path_cats(rec, sp) for sp in steps]
    assert any(
        {"query", "node", "fragment"} <= set(c) for c in chains
    ), chains
    # every executed plan node got exactly one node span, distinct ids
    node_ids = [
        sp.args["plan_node_id"] for sp in rec.spans_by_cat("node")
    ]
    assert node_ids and len(set(node_ids)) == len(node_ids)
    # cache spans exist (result-cache lookup at minimum)
    assert rec.spans_by_cat("cache")


def test_export_chrome_trace_is_valid_json(tmp_path, conn):
    s = Session({"tpch": conn}, trace_token="tok-export")
    s.sql("select count(*) c from nation")
    path = s.export_trace(str(tmp_path / "trace.json"))
    data = json.load(open(path))
    events = data["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    assert xs, "no complete events exported"
    for e in xs:
        assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["args"]["trace_token"] == "tok-export"
    # metadata names the query process
    assert any(e.get("ph") == "M" for e in events)
    assert "tok-export" in data["otherData"]["trace_tokens"]


def test_trace_disabled_records_nothing(conn):
    s = Session({"tpch": conn}, properties={"trace_enabled": False})
    s.sql("select count(*) c from nation")
    assert len(s.traces) == 0
    with pytest.raises(UserError):
        s.export_trace("/tmp/_no_trace.json")


def test_trace_max_spans_bounds_recording(conn):
    s = Session({"tpch": conn}, properties={"trace_max_spans": 3})
    s.sql("select count(*) c from nation")
    rec = s.traces.latest()
    assert len(rec.spans) <= 3
    assert rec.dropped > 0


def test_export_single_query_filter(tmp_path, conn):
    s = Session({"tpch": conn})
    s.sql("select count(*) c from nation")
    s.sql("select count(*) c from region")
    qid = s.traces.latest().query_id
    path = s.export_trace(str(tmp_path / "one.json"), query_id=qid)
    data = json.load(open(path))
    assert data["otherData"]["queries"] == [qid]
    with pytest.raises(UserError):
        s.export_trace(str(tmp_path / "x.json"), query_id="q_none")


# ---------------------------------------------------------------------------
# system tables
# ---------------------------------------------------------------------------


def test_system_query_history_phase_timings(conn):
    s = Session({"tpch": conn}, trace_token="tok-hist")
    s.sql(Q_AGG)
    s.sql(Q_AGG)  # warm: result-cache hit
    df = s.sql(
        "select query_id, state, queued_s, planning_s, execution_s, "
        "elapsed_s, cache_hit, trace_token from query_history"
    )
    assert len(df) >= 2
    assert (df["queued_s"] >= 0).all()
    assert (df["execution_s"] >= 0).all()
    assert df["planning_s"].iloc[0] > 0
    assert df["state"].iloc[0] == "FINISHED"
    assert int(df["cache_hit"].iloc[1]) == 1  # the warm repeat
    assert df["trace_token"].iloc[0] == "tok-hist"


def test_query_history_ring_is_bounded(conn):
    s = Session({"tpch": conn}, properties={"query_history_limit": 2})
    for _ in range(4):
        s.sql("select count(*) c from nation")
    assert len(s.history) == 2


def test_query_history_limit_set_property_resizes(conn):
    s = Session({"tpch": conn}, properties={"query_history_limit": 8})
    for _ in range(3):
        s.sql("select count(*) c from nation")
    s.set_property("query_history_limit", 2)
    assert len(s.history) == 2  # newest entries kept
    s.sql("select count(*) c from region")
    assert len(s.history) == 2


def test_system_trace_spans_table(conn):
    s = Session({"tpch": conn}, trace_token="tok-spans")
    s.sql("select count(*) c from nation")
    df = s.sql(
        "select query_id, span_id, parent_id, name, category, start_s, "
        "duration_s, plan_node_id, trace_token from trace_spans"
    )
    assert len(df) > 0
    assert (df["duration_s"] >= 0).all()
    assert (df["start_s"] >= 0).all()
    cats = set(df["category"])
    assert "query" in cats and "node" in cats
    assert set(df["trace_token"]) == {"tok-spans"}
    # parent ids reference spans within the same query
    roots = df[df["parent_id"] == -1]
    assert len(roots) >= 1


def test_failed_query_lands_in_history_with_error_code(conn):
    from presto_tpu.runtime.faults import FaultInjector, injected

    s = Session({"tpch": conn})
    inj = FaultInjector()
    inj.inject("scan", times=None)
    with injected(inj):
        with pytest.raises(Exception):
            s.sql("select count(*) c from nation")
    df = s.sql("select state, error_code, execution_s from query_history")
    failed = df[df["state"] == "FAILED"]
    assert len(failed) == 1
    assert failed["error_code"].iloc[0] != ""
    assert failed["execution_s"].iloc[0] >= 0


# ---------------------------------------------------------------------------
# histogram metrics
# ---------------------------------------------------------------------------


def test_histogram_stat_percentiles():
    h = HistogramStat("t")
    for v in [0.001] * 98 + [0.5, 2.0]:
        h.add(v)
    assert h.count == 100
    assert h.quantile(0.5) <= 0.0018  # bucket upper bound near 1ms
    assert h.quantile(0.99) >= 0.5
    assert h.max == 2.0
    snap = {}
    h.snapshot_into(snap)
    assert {"t.count", "t.p50", "t.p95", "t.p99", "t.max"} <= set(snap)


def test_runtime_metrics_exposes_histogram_percentiles(conn):
    s = Session({"tpch": conn})
    s.sql("select count(*) c from nation")
    df = s.sql("select name, value from runtime_metrics")
    names = set(df["name"])
    assert "query.execution_s.p50" in names
    assert "query.execution_s.p95" in names
    assert "query.execution_s.p99" in names


def test_counter_and_timer_adds_are_thread_safe():
    reg = MetricsRegistry()
    c = reg.counter("race.counter")
    t = reg.timer("race.timer")
    h = reg.histogram("race.hist")

    def bump():
        for _ in range(5000):
            c.add()
            t.add(0.001)
            h.add(0.001)

    threads = [threading.Thread(target=bump) for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert c.total == 8 * 5000
    assert t.count == 8 * 5000
    assert h.count == 8 * 5000


def test_metrics_registry_reset():
    reg = MetricsRegistry()
    reg.counter("a").add(3)
    reg.histogram("b").add(1.0)
    reg.timer("c").add(1.0)
    assert reg.snapshot()
    reg.reset()
    assert reg.snapshot() == {}


# ---------------------------------------------------------------------------
# QueryInfo phases (monotonic clock pair)
# ---------------------------------------------------------------------------


def test_queryinfo_durations_use_monotonic_pair():
    info = QueryInfo(
        query_id="q", sql="select 1", state="FINISHED",
        created_at=1e9, created_mono=100.0, started_mono=100.5,
        finished_mono=102.0, planning_s=0.25,
        started_at=5.0, finished_at=2.0,  # wall clock stepped BACKWARD
    )
    assert info.queued_s == pytest.approx(0.5)
    assert info.execution_s == pytest.approx(1.5)
    assert info.elapsed_s == pytest.approx(1.5)  # not the -3s wall delta
    d = json.loads(info.to_json())
    assert d["queuedS"] == pytest.approx(0.5)
    assert d["planningS"] == pytest.approx(0.25)
    assert d["executionS"] == pytest.approx(1.5)


def test_queryinfo_phases_populated_by_session(conn):
    s = Session({"tpch": conn})
    _df, info = s.execute("select count(*) c from nation")
    assert info.created_mono is not None
    assert info.started_mono is not None
    assert info.finished_mono is not None
    assert info.execution_s > 0
    assert info.planning_s > 0


# ---------------------------------------------------------------------------
# stable node ids (satellite: id(node) reuse bug class)
# ---------------------------------------------------------------------------


def test_node_ids_pin_nodes_against_id_reuse():
    import gc

    class FakeNode:
        children = ()

    ids = NodeIds()
    first = FakeNode()
    first_id = ids.of(first)
    addr = id(first)
    del first
    gc.collect()
    # the pinned reference keeps the object alive: no new node can
    # land on the same address and alias the id
    assert ids._pinned and id(ids._pinned[0]) == addr
    others = [FakeNode() for _ in range(64)]
    assert all(id(o) != addr for o in others)
    assert all(ids.of(o) != first_id for o in others)


def test_stats_recorder_keys_by_stable_id():
    class FakeNode:
        children = ()

    rec = StatsRecorder()
    a, b = FakeNode(), FakeNode()
    rec.record(a, 0.5, 10)
    rec.record(b, 0.25, 20)
    rec.record(a, 0.5)
    sa, sb = rec.stats_for(a), rec.stats_for(b)
    assert sa is not sb
    assert sa.wall_s == pytest.approx(1.0) and sa.invocations == 2
    assert sb.output_rows == 20
    assert sa.node_id != sb.node_id


def test_node_stats_carry_bytes_and_input_rows(conn):
    s = Session({"tpch": conn})
    _df, info = s.execute(Q_AGG)
    by_type = {st["node"]: st for st in info.node_stats}
    agg = by_type["Aggregate"]
    assert agg["output_rows"] == 4
    assert agg["input_rows"] > 100  # lineitem rows flowed in
    assert agg["output_bytes"] > 0
    assert agg["device_bytes"] >= agg["output_bytes"]
    assert agg["nodeId"] >= 0


def test_explain_analyze_enriched(conn):
    s = Session({"tpch": conn})
    out = s.explain_analyze("select count(*) c from region")
    assert "bytes" in out
    assert "rows" in out
    assert "cache: result_cache:lookup" in out


# ---------------------------------------------------------------------------
# event dispatcher guarantees (satellite)
# ---------------------------------------------------------------------------


class _OrderListener:
    def __init__(self):
        self.events = []

    def query_created(self, info):
        self.events.append(("created", info.state))

    def query_failed(self, info):
        self.events.append(("failed", info.state))

    def query_completed(self, info):
        self.events.append(("completed", info.state))

    def fragment_retried(self, info):
        self.events.append(("retried", info.fragment_retries))


def test_query_failed_fires_before_query_completed(conn):
    from presto_tpu.runtime.faults import FaultInjector, injected

    s = Session({"tpch": conn}, properties={"result_cache_enabled": False})
    listener = _OrderListener()
    s.add_event_listener(listener)
    inj = FaultInjector()
    inj.inject("scan", times=None)  # every scan fails; no retries armed
    with injected(inj):
        with pytest.raises(Exception):
            s.sql("select count(*) c from nation")
    kinds = [k for k, _ in listener.events]
    assert "failed" in kinds and "completed" in kinds
    assert kinds.index("failed") < kinds.index("completed")
    # the failed event already sees the FAILED state
    assert dict(listener.events)["failed"] == "FAILED"


def test_fragment_retried_counts_visible_to_listeners(conn):
    from presto_tpu.runtime.faults import FaultInjector, injected

    s = Session(
        {"tpch": conn},
        properties={"retry_count": 3, "retry_backoff_s": 0.0,
                    "result_cache_enabled": False},
    )
    listener = _OrderListener()
    s.add_event_listener(listener)
    inj = FaultInjector()
    inj.inject("scan", times=2)
    with injected(inj):
        df = s.sql("select count(*) c from nation")
    assert int(df["c"][0]) == 25
    retries = [n for k, n in listener.events if k == "retried"]
    # monotonically increasing counts, already incremented at fire time
    assert retries == sorted(retries) and retries[0] >= 1
    assert retries[-1] == 2


def test_listener_exceptions_swallowed_and_counted(conn):
    class Bad:
        def query_completed(self, info):
            raise RuntimeError("listener bug")

    before = REGISTRY.snapshot().get("events.listener_errors", 0)
    s = Session({"tpch": conn})
    s.add_event_listener(Bad())
    df = s.sql("select count(*) c from nation")  # must not fail
    assert int(df["c"][0]) == 25
    after = REGISTRY.snapshot().get("events.listener_errors", 0)
    assert after >= before + 1


# ---------------------------------------------------------------------------
# overhead bound (acceptance: <5% on the warm-cache Q1 path)
# ---------------------------------------------------------------------------


def test_trace_overhead_under_5pct_warm_q1(conn):
    props = {"result_cache_enabled": False}
    s_on = Session({"tpch": conn}, properties=props)
    s_off = Session(
        {"tpch": conn}, properties={**props, "trace_enabled": False}
    )
    # warm the executable caches so neither side pays trace+compile
    s_on.sql(Q_AGG)
    s_off.sql(Q_AGG)

    def best_of(rounds):
        on, off = [], []
        for _ in range(rounds):
            t0 = time.perf_counter()
            s_off.sql(Q_AGG)
            off.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            s_on.sql(Q_AGG)
            on.append(time.perf_counter() - t0)
        return min(on), min(off)

    # min-of-N interleaved runs estimates the noise-free cost; a real
    # tracing regression is systematic and survives the min. Retry once
    # with more rounds before failing: a loaded CI box can blow a 5%
    # wall-clock bound with zero code defect, and the gate must only
    # trip on the systematic case.
    for rounds in (5, 9):
        best_on, best_off = best_of(rounds)
        if best_on <= best_off * 1.05 + 0.005:
            return
    raise AssertionError(
        f"tracing overhead too high: on={best_on:.4f}s off={best_off:.4f}s"
    )


# ---------------------------------------------------------------------------
# distributed acceptance (virtual mesh; slow tier like the other
# distributed suites)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_distributed_q3_trace_acceptance(tmp_path):
    from presto_tpu.connectors.tpch.queries import QUERIES
    from presto_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    s = Session(
        {"tpch": TpchConnector(sf=0.005)}, mesh=mesh, trace_token="tok-q3"
    )
    df = s.sql(QUERIES["q3"])
    assert len(df) > 0
    rec = s.traces.latest()
    # spans nest query -> node -> fragment -> step
    steps = rec.spans_by_cat("step")
    assert any(
        {"query", "node", "fragment"} <= set(_span_path_cats(rec, sp))
        for sp in steps
    )
    # one node span per executed plan node
    plan = s.plan(QUERIES["q3"])

    def count_nodes(n):
        return 1 + sum(count_nodes(c) for c in n.children)

    node_ids = {sp.args["plan_node_id"] for sp in rec.spans_by_cat("node")}
    assert len(node_ids) == count_nodes(plan)
    # exchange spans carry nonzero byte counts
    ex = rec.spans_by_cat("exchange")
    assert ex and sum(sp.args["bytes"] for sp in ex) > 0
    assert all(sp.args["rounds"] >= 1 for sp in ex)
    # exported JSON carries the trace token on every span
    path = s.export_trace(str(tmp_path / "q3.json"))
    data = json.load(open(path))
    xs = [e for e in data["traceEvents"] if e.get("ph") == "X"]
    assert xs and all(e["args"]["trace_token"] == "tok-q3" for e in xs)
    # history row with phase timings
    hist = s.sql(
        "select query_id, execution_s, planning_s from query_history"
    )
    assert len(hist) == 1 and hist["execution_s"].iloc[0] > 0
