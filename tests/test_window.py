"""Window function tests: kernels vs numpy, SQL vs pandas oracle
(reference parity: TestWindowOperator + window function query tests in
AbstractTestQueries [SURVEY §4])."""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.ops.window import (
    change_flags,
    rank_values,
    seg_scan,
    segment_ends,
    segment_starts,
    windowed_agg,
)
from presto_tpu.runtime.session import Session

from tests.test_tpch_sql import compare

SF = 0.005


@pytest.fixture(scope="module")
def env():
    conn = TpchConnector(sf=SF, units_per_split=1 << 14)
    session = Session({"tpch": conn})
    tables = {name: conn.table_pandas(name) for name in conn.tables()}
    return session, tables


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def test_seg_scan_matches_loop(rng):
    n = 257
    vals = rng.integers(-50, 50, n).astype(np.int64)
    reset = rng.random(n) < 0.15
    reset[0] = True
    for kind, op in (("sum", np.add), ("min", np.minimum), ("max", np.maximum)):
        got = np.asarray(seg_scan(jnp.asarray(vals), jnp.asarray(reset), kind))
        want = np.empty(n, np.int64)
        for i in range(n):
            want[i] = vals[i] if reset[i] else op(want[i - 1], vals[i])
        np.testing.assert_array_equal(got, want, err_msg=kind)


def test_segment_starts_ends():
    flags = jnp.asarray([True, False, False, True, False, True])
    np.testing.assert_array_equal(
        np.asarray(segment_starts(flags)), [0, 0, 0, 3, 3, 5]
    )
    np.testing.assert_array_equal(
        np.asarray(segment_ends(flags)), [2, 2, 2, 4, 4, 5]
    )


def test_rank_values_with_ties():
    # two partitions: [a a b b b] with order values [1 1 2 2 3]
    part = jnp.asarray([True, False, True, False, False])
    peer = jnp.asarray([True, False, True, False, True])
    rn, rk, dr = rank_values(part, peer)
    np.testing.assert_array_equal(np.asarray(rn), [1, 2, 1, 2, 3])
    np.testing.assert_array_equal(np.asarray(rk), [1, 1, 1, 1, 3])
    np.testing.assert_array_equal(np.asarray(dr), [1, 1, 1, 1, 2])


def test_windowed_agg_null_and_frames():
    # one partition of 4 rows + a second of 2; row 1 doesn't contribute
    part = jnp.asarray([True, False, False, False, True, False])
    peer = jnp.asarray([True, False, True, False, True, True])  # peers: {0,1},{2,3},{4},{5}
    vals = jnp.asarray([10, 99, 5, 7, 3, 4], jnp.int64)
    contrib = jnp.asarray([True, False, True, True, True, True])
    v, c = windowed_agg(vals, contrib, part, peer, "sum", "rows")
    np.testing.assert_array_equal(np.asarray(v), [10, 10, 15, 22, 3, 7])
    np.testing.assert_array_equal(np.asarray(c), [1, 1, 2, 3, 1, 2])
    v, c = windowed_agg(vals, contrib, part, peer, "sum", "range")
    # peers share the frame end: rows 0,1 -> value at row 1; rows 2,3 -> at 3
    np.testing.assert_array_equal(np.asarray(v), [10, 10, 22, 22, 3, 7])
    v, c = windowed_agg(vals, contrib, part, peer, "sum", "full")
    np.testing.assert_array_equal(np.asarray(v), [22, 22, 22, 22, 7, 7])


def test_change_flags_nulls_compare():
    data = jnp.asarray([1, 1, 1, 2], jnp.int64)
    valid = jnp.asarray([True, False, False, True])
    f = change_flags([jnp.where(valid, data, 0)], [valid])
    np.testing.assert_array_equal(np.asarray(f), [True, True, False, True])


# ---------------------------------------------------------------------------
# SQL vs pandas
# ---------------------------------------------------------------------------


def test_rank_per_partition(env):
    session, t = env
    got = session.sql(
        "select n_name, n_regionkey, "
        "rank() over (partition by n_regionkey order by n_name) as rk "
        "from nation"
    )
    n = t["nation"].copy()
    n["rk"] = n.groupby("n_regionkey")["n_name"].rank(method="min").astype(np.int64)
    compare(got, n[["n_name", "n_regionkey", "rk"]], "rank_per_partition")


def test_row_number_unique_order(env):
    session, t = env
    got = session.sql(
        "select s_suppkey, row_number() over (order by s_suppkey desc) as rn "
        "from supplier"
    )
    s = t["supplier"].copy().sort_values("s_suppkey", ascending=False)
    s["rn"] = np.arange(1, len(s) + 1)
    compare(got, s[["s_suppkey", "rn"]], "row_number")


def test_partition_aggregates(env):
    session, t = env
    got = session.sql(
        "select o_orderkey, o_custkey, "
        "sum(o_totalprice) over (partition by o_custkey) as tot, "
        "avg(o_totalprice) over (partition by o_custkey) as av, "
        "max(o_totalprice) over (partition by o_custkey) as mx, "
        "count(*) over (partition by o_custkey) as cnt "
        "from orders"
    )
    o = t["orders"].copy()
    g = o.groupby("o_custkey")["o_totalprice"]
    o["tot"] = g.transform("sum")
    o["av"] = g.transform("mean")
    o["mx"] = g.transform("max")
    o["cnt"] = o.groupby("o_custkey")["o_orderkey"].transform("size").astype(np.int64)
    compare(
        got, o[["o_orderkey", "o_custkey", "tot", "av", "mx", "cnt"]],
        "partition_aggregates",
    )


def test_dense_rank_with_ties(env):
    session, t = env
    got = session.sql(
        "select c_custkey, "
        "dense_rank() over (partition by c_nationkey order by c_mktsegment) as dr "
        "from customer"
    )
    c = t["customer"].copy()
    c["dr"] = (
        c.groupby("c_nationkey")["c_mktsegment"].rank(method="dense").astype(np.int64)
    )
    compare(got, c[["c_custkey", "dr"]], "dense_rank")


def test_running_sum_rows_frame(env):
    session, t = env
    got = session.sql(
        "select ps_partkey, ps_suppkey, "
        "sum(ps_availqty) over (partition by ps_suppkey order by ps_partkey "
        "rows between unbounded preceding and current row) as run "
        "from partsupp"
    )
    ps = t["partsupp"].copy().sort_values(["ps_suppkey", "ps_partkey"])
    ps["run"] = ps.groupby("ps_suppkey")["ps_availqty"].cumsum().astype(np.int64)
    compare(got, ps[["ps_partkey", "ps_suppkey", "run"]], "running_sum_rows")


def test_running_sum_range_peers(env):
    session, t = env
    got = session.sql(
        "select o_orderkey, "
        "sum(o_totalprice) over (partition by o_custkey order by o_orderdate) as run "
        "from orders"
    )
    o = t["orders"].copy()

    def per_group(g):
        g = g.sort_values("o_orderdate")
        run = g["o_totalprice"].cumsum()
        # RANGE frame: peers (equal o_orderdate) share the last peer's value
        last = run.groupby(g["o_orderdate"].values).transform("last")
        return pd.DataFrame({"o_orderkey": g["o_orderkey"], "run": last})

    want = (
        o.groupby("o_custkey", group_keys=False)[["o_custkey", "o_orderkey",
                                                  "o_totalprice", "o_orderdate"]]
        .apply(per_group)
        .reset_index(drop=True)
    )
    compare(got, want[["o_orderkey", "run"]], "running_sum_range")


def test_window_over_group_by(env):
    session, t = env
    got = session.sql(
        "select l_returnflag, l_linestatus, sum(l_quantity) as s, "
        "rank() over (order by sum(l_quantity) desc) as rk "
        "from lineitem group by l_returnflag, l_linestatus"
    )
    li = t["lineitem"].groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
        s=("l_quantity", "sum")
    )
    li["rk"] = li["s"].rank(method="min", ascending=False).astype(np.int64)
    compare(got, li, "window_over_group_by")


def test_topn_per_group_via_subquery(env):
    session, t = env
    got = session.sql(
        "select s_suppkey, s_nationkey, rk from ("
        "select s_suppkey, s_nationkey, "
        "rank() over (partition by s_nationkey order by s_acctbal desc) as rk "
        "from supplier) ranked where rk <= 2"
    )
    s = t["supplier"].copy()
    s["rk"] = (
        s.groupby("s_nationkey")["s_acctbal"]
        .rank(method="min", ascending=False)
        .astype(np.int64)
    )
    want = s[s["rk"] <= 2]
    compare(got, want[["s_suppkey", "s_nationkey", "rk"]], "topn_per_group")


def test_explain_shows_window(env):
    session, _ = env
    txt = session.explain(
        "select rank() over (partition by n_regionkey order by n_name) from nation"
    )
    assert "Window" in txt


def test_window_in_where_rejected(env):
    session, _ = env
    from presto_tpu.sql.analyzer import AnalysisError

    with pytest.raises(AnalysisError):
        session.plan(
            "select n_name from nation "
            "where rank() over (order by n_name) <= 2"
        )


def test_window_only_in_order_by(env):
    session, t = env
    got = session.sql(
        "select n_name from nation "
        "order by rank() over (order by n_name desc)"
    )
    want = t["nation"].sort_values("n_name", ascending=False)[["n_name"]]
    assert got["n_name"].tolist() == want["n_name"].tolist()
    assert list(got.columns) == ["n_name"]


def test_select_star_does_not_leak_window_columns(env):
    session, _ = env
    got = session.sql(
        "select *, rank() over (order by n_name) as rk from nation"
    )
    assert list(got.columns) == [
        "n_nationkey", "n_name", "n_regionkey", "n_comment", "rk"
    ]


def test_wide_bytes_window_keys(env):
    session, t = env
    got = session.sql(
        "select s_suppkey, rank() over (order by s_name) as rk from supplier"
    )
    s = t["supplier"].copy()
    s["rk"] = s["s_name"].rank(method="min").astype(np.int64)
    compare(got, s[["s_suppkey", "rk"]], "wide_bytes_order_key")
    got = session.sql(
        "select s_suppkey, "
        "count(*) over (partition by s_name) as c from supplier"
    )
    s["c"] = s.groupby("s_name")["s_suppkey"].transform("size").astype(np.int64)
    compare(got, s[["s_suppkey", "c"]], "wide_bytes_partition_key")


def test_window_minmax_dictionary_and_bytes(env):
    from presto_tpu.sql.analyzer import AnalysisError

    session, t = env
    got = session.sql(
        "select c_custkey, max(c_mktsegment) over (partition by c_nationkey) mx "
        "from customer"
    )
    c = t["customer"].copy()
    c["mx"] = c.groupby("c_nationkey")["c_mktsegment"].transform("max")
    compare(got, c[["c_custkey", "mx"]], "window_max_dict")
    with pytest.raises(AnalysisError):
        session.plan(
            "select min(s_name) over (partition by s_nationkey) from supplier"
        )


def test_window_agg_without_args_rejected(env):
    from presto_tpu.sql.analyzer import AnalysisError

    session, _ = env
    with pytest.raises(AnalysisError):
        session.plan("select sum() over () from nation")


def test_window_distributed_matches_local(env):
    from presto_tpu.parallel.mesh import make_mesh

    session, t = env
    mesh = make_mesh(8)
    dist = Session({"tpch": session.catalog.connector("tpch")}, mesh=mesh)
    q = (
        "select n_name, n_regionkey, "
        "rank() over (partition by n_regionkey order by n_name) as rk "
        "from nation"
    )
    compare(dist.sql(q), session.sql(q), "window_distributed")


def test_lag_lead_first_value(env):
    session, tables = env
    import numpy as np

    df = session.sql(
        "select l_orderkey k, l_linenumber ln, "
        "lag(l_quantity) over (partition by l_orderkey order by l_linenumber) p1, "
        "lag(l_quantity, 2) over (partition by l_orderkey order by l_linenumber) p2, "
        "lead(l_quantity) over (partition by l_orderkey order by l_linenumber) nx, "
        "first_value(l_quantity) over (partition by l_orderkey order by l_linenumber) fv "
        "from lineitem order by k, ln limit 300"
    )
    li = tables["lineitem"].sort_values(["l_orderkey", "l_linenumber"])
    g = li.groupby("l_orderkey")["l_quantity"]
    want = li.assign(p1=g.shift(1), p2=g.shift(2), nx=g.shift(-1),
                     fv=g.transform("first")).head(300)
    for c in ("p1", "p2", "nx", "fv"):
        np.testing.assert_allclose(
            df[c].astype(float).to_numpy(), want[c].astype(float).to_numpy(),
            rtol=1e-9, equal_nan=True, err_msg=c,
        )


def test_lag_requires_order_by(env):
    session, _ = env
    import pytest

    with pytest.raises(Exception, match="requires ORDER BY"):
        session.sql(
            "select lag(l_quantity) over (partition by l_orderkey) x from lineitem"
        )
